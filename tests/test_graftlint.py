"""graftlint: the static-analysis suite that encodes this repo's shipped
bug classes as enforced rules (``improved_body_parts_tpu/analysis/``).

Contract per rule (the fixture triplet):

- a *bad* snippet reproducing the bug class must flag;
- the *fixed* idiom that repaired it must pass (false-positive guard);
- a *suppressed* site (``# graftlint: disable=... -- reason``) must
  stay silent, and a reasonless pragma must both NOT suppress and be an
  error itself (JGL000).

Plus the historical regressions verbatim-shaped: PR 5's snapshot-view
read and PR 3's per-batch ``float(loss)`` loop — the two postmortems
the suite exists for — and the tier-1 self-scan gate
(:func:`test_self_scan_clean`) that keeps the real tree at zero
error-severity findings.

No jax import anywhere in the linter path: these tests run on a bare
interpreter.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from improved_body_parts_tpu.analysis import (  # noqa: E402
    GRAFTLINT_VERSION,
    LintConfig,
    all_rules,
    lint_paths,
    lint_source,
    load_config,
    ruleset_hash,
)
from improved_body_parts_tpu.analysis.config import (  # noqa: E402
    ConfigError,
    config_from_tables,
    parse_graftlint_tables,
)

TRAIN_PATH = "improved_body_parts_tpu/train/snippet.py"


def lint(src, path=TRAIN_PATH, config=None):
    findings, _ = lint_source(textwrap.dedent(src), path, config)
    return findings


def rules_of(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------- framework


class TestFramework:
    def test_rule_table_is_complete(self):
        ids = {r.id for r in all_rules()}
        assert ids == {"JGL001", "JGL002", "JGL003", "JGL004",
                       "JGL005", "JGL006", "JGL007", "JGL008"}
        for r in all_rules():
            assert r.postmortem, f"{r.id} lacks its postmortem pointer"

    def test_ruleset_hash_is_stable_and_version_present(self):
        h = ruleset_hash()
        assert h == ruleset_hash()
        assert len(h) == 12 and int(h, 16) >= 0
        assert GRAFTLINT_VERSION.count(".") == 2

    def test_syntax_error_reports_not_silently_clean(self):
        findings = lint("def broken(:\n    pass\n")
        assert rules_of(findings) == ["JGL000"]
        assert "does not parse" in findings[0].message

    def test_findings_carry_position_and_serialize(self):
        findings = lint("""
            import json
            json.dumps({"a": 1.0})
        """)
        (f,) = findings
        assert f.rule == "JGL004" and f.line == 3 and f.col > 0
        assert json.loads(json.dumps(f.as_dict(),
                                     allow_nan=False))["path"] == TRAIN_PATH

    def test_disable_via_config(self):
        cfg = LintConfig(disable=("JGL004",))
        assert lint("import json\njson.dumps({})\n", config=cfg) == []

    def test_severity_override_via_config(self):
        cfg = LintConfig(severity={"JGL004": "info"})
        (f,) = lint("import json\njson.dumps({})\n", config=cfg)
        assert f.severity == "info"

    def test_tests_downgrade_errors_to_warnings(self):
        src = "import json\njson.dumps({})\n"
        (f,) = lint(src, path="tests/test_x.py")
        assert f.severity == "warning"
        (f,) = lint(src, path="tools/x.py")
        assert f.severity == "error"
        cfg = LintConfig(tests_downgrade=False)
        (f,) = lint(src, path="tests/test_x.py", config=cfg)
        assert f.severity == "error"


class TestSuppressions:
    BAD = "import json\njson.dumps({})  # graftlint: disable=JGL004%s\n"

    def test_suppression_with_reason_is_silent_and_counted(self):
        findings, suppressed = lint_source(
            self.BAD % " -- fixture data is finite by construction",
            TRAIN_PATH)
        assert findings == [] and suppressed == 1

    def test_reasonless_pragma_does_not_suppress_and_is_an_error(self):
        findings, suppressed = lint_source(self.BAD % "", TRAIN_PATH)
        assert suppressed == 0
        assert sorted(rules_of(findings)) == ["JGL000", "JGL004"]
        jgl0 = next(f for f in findings if f.rule == "JGL000")
        assert "requires a reason" in jgl0.message

    def test_unknown_rule_id_in_pragma_is_an_error(self):
        findings, _ = lint_source(
            "x = 1  # graftlint: disable=JGL999 -- whatever\n", TRAIN_PATH)
        assert rules_of(findings) == ["JGL000"]
        assert "JGL999" in findings[0].message

    def test_pragma_anywhere_on_multiline_statement_suppresses(self):
        src = ("import json\n"
               "json.dumps(\n"
               "    {'a': 1},\n"
               ")  # graftlint: disable=JGL004 -- demo payload, finite\n")
        findings, suppressed = lint_source(src, TRAIN_PATH)
        assert findings == [] and suppressed == 1

    def test_pragma_in_docstring_is_not_a_suppression(self):
        src = ('"""docs: use # graftlint: disable=JGL004 like this"""\n'
               "import json\n"
               "json.dumps({})\n")
        findings, _ = lint_source(src, TRAIN_PATH)
        assert rules_of(findings) == ["JGL004"]

    def test_disable_all_with_reason(self):
        findings, suppressed = lint_source(
            "import json\n"
            "json.dumps({})  # graftlint: disable=all -- generated code\n",
            TRAIN_PATH)
        assert findings == [] and suppressed == 1


class TestConfigParsing:
    SECTION = """
        [project]
        name = "x"

        [tool.graftlint]
        paths = [
            "pkg",
            "tools",
        ]
        disable = ["jgl007"]
        donating-factories = ["make_train_step:0", "make_other:1,2"]
        tests-downgrade = false

        [tool.graftlint.severity]
        JGL005 = "info"

        [tool.other]
        irrelevant = { not = "parsed" }
    """

    def test_parse_subset(self):
        cfg = config_from_tables(parse_graftlint_tables(
            textwrap.dedent(self.SECTION)))
        assert cfg.paths == ("pkg", "tools")
        assert cfg.disable == ("JGL007",)
        assert cfg.tests_downgrade is False
        assert cfg.severity == {"JGL005": "info"}
        assert cfg.donated_positions("make_other") == (1, 2)
        assert cfg.donated_positions("make_train_step") == (0,)
        assert cfg.donated_positions("unknown") is None

    def test_bad_severity_is_loud(self):
        with pytest.raises(ConfigError):
            config_from_tables({"severity": {"JGL001": "fatal"}})

    def test_unknown_key_is_loud(self):
        with pytest.raises(ConfigError):
            config_from_tables({"": {"typo_key": ["x"]}})

    def test_repo_config_loads(self):
        cfg = load_config(REPO)
        assert "improved_body_parts_tpu" in cfg.paths
        assert "tests" in cfg.paths
        assert cfg.donated_positions("make_train_step") == (0,)


# ------------------------------------------------------- JGL001 donation


class TestDonationSafety:
    def test_read_after_donation_flags(self):
        findings = lint("""
            import jax

            step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))

            def bad(state, batch):
                new_state = step(state, batch)
                return float(state.mean()), new_state
        """)
        assert "JGL001" in rules_of(findings)

    def test_rebinding_passes(self):
        findings = lint("""
            import jax

            step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))

            def good(state, batch):
                state = step(state, batch)
                return float(state.mean()), state
        """)
        assert [f for f in findings if f.rule == "JGL001"] == []

    def test_unrebound_donation_in_loop_flags_the_call(self):
        findings = lint("""
            import jax

            step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))

            def bad(state, batches):
                for batch in batches:
                    out = step(state, batch)
        """)
        assert "JGL001" in rules_of(findings)
        assert "next" in next(f.message for f in findings
                              if f.rule == "JGL001")

    def test_rebound_donation_in_loop_passes(self):
        findings = lint("""
            import jax

            step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))

            def good(state, batches):
                for batch in batches:
                    state = step(state, batch)
                return state
        """)
        assert [f for f in findings if f.rule == "JGL001"] == []

    def test_configured_factory_donates(self):
        findings = lint("""
            from improved_body_parts_tpu.train.step import make_train_step

            def bad(model, cfg, opt, state, batch):
                step = make_train_step(model, cfg, opt)
                new_state, loss = step(state, batch)
                return state.params
        """)
        assert "JGL001" in rules_of(findings)

    def test_factory_with_donate_false_passes(self):
        findings = lint("""
            from improved_body_parts_tpu.train.step import make_train_step

            def good(model, cfg, opt, state, batch):
                step = make_train_step(model, cfg, opt, donate=False)
                new_state, loss = step(state, batch)
                return state.params
        """)
        assert [f for f in findings if f.rule == "JGL001"] == []

    def test_pr5_snapshot_view_regression(self):
        """The PR 5 bug, verbatim shape: a zero-copy ``np.asarray`` view
        of donatable state escaping the snapshot uncopied."""
        findings = lint("""
            import jax
            import numpy as np

            step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))

            def snapshot_to_host(tree):
                def drain(x):
                    return np.asarray(x)
                return jax.tree.map(drain, tree)
        """)
        assert "JGL001" in rules_of(findings)
        assert "zero-copy" in next(f.message for f in findings
                                   if f.rule == "JGL001")

    def test_pr5_snapshot_fix_passes(self):
        """The shipped repair: conditional ``.copy()`` when the view
        does not own its memory (train/checkpoint.py)."""
        findings = lint("""
            import jax
            import numpy as np

            step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))

            def snapshot_to_host(tree):
                def drain(x):
                    arr = np.asarray(x)
                    if isinstance(x, jax.Array) and not arr.flags.owndata:
                        arr = arr.copy()
                    return arr
                return jax.tree.map(drain, tree)
        """)
        assert [f for f in findings if f.rule == "JGL001"] == []

    def test_suppressed_site_is_silent(self):
        findings, suppressed = lint_source(textwrap.dedent("""
            import jax

            step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))

            def probe(state, batch):
                new = step(state, batch)
                return state.x  # graftlint: disable=JGL001 -- the read IS the test: donated leaves must report deleted
        """), TRAIN_PATH)
        assert [f for f in findings if f.rule == "JGL001"] == []
        assert suppressed == 1


# ------------------------------------------------------ JGL002 host sync


class TestHiddenHostSync:
    def test_pr3_float_loss_per_batch_regression(self):
        """The PR 3 bug, verbatim shape (train/loop.py eval_epoch before
        the fix): float(loss) on every batch."""
        findings = lint("""
            def eval_epoch(state, eval_step, batches, losses):
                for batch in batches:
                    loss = eval_step(state, *batch)
                    losses.update(float(loss), batch[0].shape[0])
                return losses.avg
        """)
        assert "JGL002" in rules_of(findings)

    def test_pr3_windowed_readback_fix_passes(self):
        """The shipped repair: buffer device scalars, drain in windows."""
        findings = lint("""
            def eval_epoch(state, eval_step, batches, losses):
                pending = []
                for batch in batches:
                    pending.append((eval_step(state, *batch),
                                    batch[0].shape[0]))
                    if len(pending) >= 32:
                        for loss, bs in pending:
                            losses.update(float(loss), bs)
                        pending.clear()
                for loss, bs in pending:
                    losses.update(float(loss), bs)
                return losses.avg
        """)
        assert [f for f in findings if f.rule == "JGL002"] == []

    def test_item_and_device_get_flag_too(self):
        src = """
            import jax
            import jax.numpy as jnp

            def serve_loop(requests):
                for r in requests:
                    out = jnp.sum(r)
                    yield {}.get(out.item())
        """
        findings = lint(src, path="improved_body_parts_tpu/serve/x.py")
        assert "JGL002" in rules_of(findings)

    def test_scope_is_train_serve_infer_only(self):
        src = """
            import jax.numpy as jnp

            def host_tool(batches):
                for b in batches:
                    v = jnp.sum(b)
                    print(float(v))
        """
        assert "JGL002" not in rules_of(
            lint(src, path="improved_body_parts_tpu/data/x.py"))
        assert "JGL002" not in rules_of(lint(src, path="tools/x.py"))
        assert "JGL002" in rules_of(
            lint(src, path="improved_body_parts_tpu/infer/x.py"))

    def test_fastpath_per_frame_code_is_scope_locked(self):
        """The stream fast path's decision/delivery code runs on the
        serve completion threads once per frame — a hidden device sync
        there stalls every stream behind one session.  Lock
        ``stream/fastpath.py`` into the JGL002 scope so a scope
        refactor cannot silently drop the per-frame tier machinery."""
        src = """
            import jax.numpy as jnp

            def on_delivered(frames, reasons):
                for f in frames:
                    score = jnp.min(f)
                    reasons.append(float(score))
        """
        assert "JGL002" in rules_of(
            lint(src, path="improved_body_parts_tpu/stream/fastpath.py"))

    def test_untainted_host_values_pass(self):
        findings = lint("""
            import numpy as np

            def stats(rows):
                out = []
                for r in rows:
                    out.append(float(np.mean(r)))
                return out
        """)
        assert [f for f in findings if f.rule == "JGL002"] == []

    def test_suppressed_warmup_sync_is_silent(self):
        findings, suppressed = lint_source(textwrap.dedent("""
            import jax

            def warmup(shapes, compiled, x):
                for s in shapes:
                    out = compiled.apply(x, s)
                    jax.block_until_ready(out)  # graftlint: disable=JGL002 -- warmup precompile: one sync per shape is the point
        """), TRAIN_PATH)
        assert [f for f in findings if f.rule == "JGL002"] == []
        assert suppressed == 1


# ------------------------------------------------------ JGL003 recompile


class TestRecompileHazard:
    def test_jit_of_loop_local_lambda_flags(self):
        findings = lint("""
            import jax

            def sweep(xs):
                outs = []
                for x in xs:
                    f = jax.jit(lambda v: v + x)
                    outs.append(f(x))
                return outs
        """)
        assert "JGL003" in rules_of(findings)

    def test_cached_jit_behind_dict_miss_guard_passes(self):
        findings = lint("""
            import jax

            def precompile(shapes, fn, cache):
                for s in shapes:
                    if s not in cache:
                        cache[s] = jax.jit(lambda v: fn(v, s))
                return cache
        """)
        assert [f for f in findings if f.rule == "JGL003"] == []

    def test_hoisted_jit_passes(self):
        findings = lint("""
            import jax

            def run(xs, fn):
                f = jax.jit(fn)
                return [f(x) for x in xs]
        """)
        assert [f for f in findings if f.rule == "JGL003"] == []

    def test_mutable_static_arg_flags(self):
        findings = lint("""
            import jax

            def kernel(x, opts):
                return x

            f = jax.jit(kernel, static_argnums=(1,))

            def call(x):
                return f(x, {"mode": "fast"})
        """)
        assert "JGL003" in rules_of(findings)

    def test_hashable_static_arg_passes(self):
        findings = lint("""
            import jax

            def kernel(x, opts):
                return x

            f = jax.jit(kernel, static_argnums=(1,))

            def call(x):
                return f(x, ("fast",))
        """)
        assert [f for f in findings if f.rule == "JGL003"] == []

    def test_closure_over_mutated_name_flags(self):
        findings = lint("""
            import jax

            def build(x):
                scales = [1.0]

                def fwd(v):
                    return v * scales[0]

                f = jax.jit(fwd)
                scales.append(2.0)
                return f
        """)
        assert "JGL003" in rules_of(findings)

    def test_closure_over_constant_passes(self):
        findings = lint("""
            import jax

            def build(x, scale):
                def fwd(v):
                    return v * scale

                return jax.jit(fwd)
        """)
        assert [f for f in findings if f.rule == "JGL003"] == []

    def test_suppressed_site_is_silent(self):
        findings, suppressed = lint_source(textwrap.dedent("""
            import jax

            def sweep(xs):
                for x in xs:
                    f = jax.jit(lambda v: v + x)  # graftlint: disable=JGL003 -- one compile per grid point is the benchmark protocol
                    f(x)
        """), TRAIN_PATH)
        assert [f for f in findings if f.rule == "JGL003"] == []
        assert suppressed == 1


# ----------------------------------------------------- JGL004 strict json


class TestStrictJson:
    def test_bare_dumps_flags(self):
        assert "JGL004" in rules_of(lint(
            "import json\njson.dumps({'loss': 1.0})\n"))

    def test_strict_idioms_pass(self):
        findings = lint("""
            import json
            from improved_body_parts_tpu.obs.events import (
                _definan,
                strict_dumps,
            )

            def emit(rec, f):
                a = json.dumps(rec, allow_nan=False)
                b = json.dumps(_definan(rec))
                c = strict_dumps(rec)
                f.write(a + b + c)
        """)
        assert [f for f in findings if f.rule == "JGL004"] == []

    def test_events_py_implementation_site_exempt(self):
        src = "import json\njson.dumps({'x': 1.0})\n"
        assert "JGL004" not in rules_of(lint(
            src, path="improved_body_parts_tpu/obs/events.py"))


# ------------------------------------------------------ JGL005 lifecycle


class TestResourceLifecycle:
    def test_unjoined_thread_flags(self):
        findings = lint("""
            import threading

            def fire_and_forget(fn):
                t = threading.Thread(target=fn)
                t.start()
        """)
        assert "JGL005" in rules_of(findings)

    def test_joined_thread_passes(self):
        findings = lint("""
            import threading

            def run(fn):
                t = threading.Thread(target=fn)
                t.start()
                try:
                    fn()
                finally:
                    t.join()
        """)
        assert [f for f in findings if f.rule == "JGL005"] == []

    def test_daemon_thread_exempt(self):
        findings = lint("""
            import threading

            def background(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
        """)
        assert [f for f in findings if f.rule == "JGL005"] == []

    def test_self_stored_and_returned_exempt(self):
        findings = lint("""
            import threading

            class Owner:
                def start(self, fn):
                    self._t = threading.Thread(target=fn)
                    self._t.start()

            def make(fn):
                t = threading.Thread(target=fn)
                return t
        """)
        assert [f for f in findings if f.rule == "JGL005"] == []

    def test_pool_and_shared_memory_flag(self):
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor
            from multiprocessing import shared_memory

            def leaky(n):
                pool = ThreadPoolExecutor(4)
                shm = shared_memory.SharedMemory(create=True, size=n)
                pool.submit(print, shm.name)
        """)
        assert rules_of([f for f in findings
                         if f.rule == "JGL005"]) == ["JGL005", "JGL005"]

    def test_container_cleanup_loop_passes(self):
        findings = lint("""
            import threading

            def fan_out(fns):
                threads = []
                for fn in fns:
                    threads.append(threading.Thread(target=fn))
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
        """)
        assert [f for f in findings if f.rule == "JGL005"] == []

    def test_suppressed_site_is_silent(self):
        findings, suppressed = lint_source(textwrap.dedent("""
            import threading

            def detach(fn):
                t = threading.Thread(target=fn)  # graftlint: disable=JGL005 -- intentionally outlives the caller; reaped by the supervisor
                t.start()
        """), TRAIN_PATH)
        assert [f for f in findings if f.rule == "JGL005"] == []
        assert suppressed == 1


# ---------------------------------------------------- JGL006 metric names


class TestMetricNames:
    def test_counter_without_total_flags(self):
        findings = lint("""
            def instrument(registry):
                registry.counter("requests")
        """)
        assert "JGL006" in rules_of(findings)
        assert "_total" in findings[0].message

    def test_bad_charset_flags(self):
        findings = lint("""
            def instrument(registry):
                registry.gauge("ring.free-slots")
        """)
        assert "JGL006" in rules_of(findings)

    def test_suffix_literal_checked(self):
        findings = lint("""
            def instrument(registry, prefix):
                registry.counter(prefix + "_stalls")
        """)
        assert "JGL006" in rules_of(findings)

    def test_good_names_pass(self):
        findings = lint("""
            def instrument(registry, prefix):
                registry.counter("requests_total")
                registry.counter(prefix + "_stalls_total")
                registry.gauge("ring_free_slots")
                registry.histogram("step_seconds",
                                   labels={"worker": "0"})
        """)
        assert [f for f in findings if f.rule == "JGL006"] == []

    def test_bad_label_key_flags(self):
        findings = lint("""
            def instrument(registry):
                registry.gauge("ring_free_slots",
                               labels={"worker-id": "0"})
        """)
        assert "JGL006" in rules_of(findings)

    def test_suppressed_site_is_silent(self):
        findings, suppressed = lint_source(textwrap.dedent("""
            def instrument(registry):
                registry.counter("legacy.requests")  # graftlint: disable=JGL006 -- legacy dashboard name; Registry sanitizes at exposition
        """), TRAIN_PATH)
        assert [f for f in findings if f.rule == "JGL006"] == []
        assert suppressed == 1


# ------------------------------------------------------ JGL007 bare print


class TestBarePrint:
    def test_library_print_flags(self):
        assert "JGL007" in rules_of(lint(
            "print('hello')\n",
            path="improved_body_parts_tpu/infer/x.py"))

    def test_tools_and_tests_exempt(self):
        assert "JGL007" not in rules_of(lint("print('x')\n",
                                             path="tools/x.py"))
        assert "JGL007" not in rules_of(lint("print('x')\n",
                                             path="tests/test_x.py"))

    def test_sink_fallback_pattern_passes_with_reason(self):
        findings, suppressed = lint_source(textwrap.dedent("""
            from ..obs.events import get_sink

            def report(event, text, **fields):
                sink = get_sink()
                if sink.enabled:
                    sink.emit(event, **fields)
                else:
                    print(text)  # graftlint: disable=JGL007 -- stdout fallback when no sink installed
        """), "improved_body_parts_tpu/infer/x.py")
        assert findings == [] and suppressed == 1


# --------------------------------------------------- JGL008 dtype hygiene


class TestDtypeHygiene:
    def test_np_float64_dtype_kwarg_into_jnp_flags(self):
        assert "JGL008" in rules_of(lint(
            "import jax.numpy as jnp\nimport numpy as np\n"
            "x = jnp.zeros((4, 4), dtype=np.float64)\n"))

    def test_string_float64_and_bare_float_flag(self):
        assert "JGL008" in rules_of(lint(
            "import jax.numpy as jnp\n"
            "x = jnp.asarray(v, dtype='float64')\n"))
        assert "JGL008" in rules_of(lint(
            "import jax.numpy as jnp\n"
            "x = jnp.full((2,), 0.0, dtype=float)\n"))

    def test_jnp_float64_attribute_flags(self):
        assert "JGL008" in rules_of(lint(
            "import jax.numpy as jnp\n"
            "y = x.astype(jnp.float64)\n"))

    def test_astype_f64_feeding_jnp_call_flags(self):
        assert "JGL008" in rules_of(lint(
            "import jax.numpy as jnp\nimport numpy as np\n"
            "d = jnp.asarray(rows.astype(np.float64))\n"))

    def test_f32_and_host_side_f64_pass(self):
        # the fixed idiom: f32 on device...
        assert rules_of(lint(
            "import jax.numpy as jnp\nimport numpy as np\n"
            "x = jnp.zeros((4, 4), dtype=jnp.float32)\n"
            "y = jnp.asarray(v, dtype=np.float32)\n")) == []
        # ...and HOST f64 untouched (decode/OKS reference parity)
        assert rules_of(lint(
            "import numpy as np\n"
            "ids = np.arange(8, dtype=np.float64)\n"
            "r = rows.astype(np.float64)\n")) == []

    def test_tools_and_tests_out_of_scope(self):
        src = ("import jax.numpy as jnp\nimport numpy as np\n"
               "x = jnp.zeros((4,), dtype=np.float64)\n")
        assert rules_of(lint(src, path="tools/x.py")) == []
        assert rules_of(lint(src, path="tests/test_x.py")) == []

    def test_suppressed_with_reason_is_silent(self):
        findings, suppressed = lint_source(textwrap.dedent("""
            import jax.numpy as jnp
            import numpy as np
            x = jnp.zeros((4,), dtype=np.float64)  # graftlint: disable=JGL008 -- x64 parity harness needs real f64
        """), TRAIN_PATH)
        assert findings == [] and suppressed == 1


# ------------------------------------------------------------- self scan


@pytest.fixture(scope="module")
def self_scan():
    config = load_config(REPO)
    return lint_paths(list(config.paths), REPO, config)


def test_self_scan_clean(self_scan):
    """The tier-1 gate: the real tree has zero error-severity findings.
    New code that reintroduces a postmortem pattern fails HERE, with the
    rule's message naming the original incident."""
    errors = [f for f in self_scan.findings if f.severity == "error"]
    assert errors == [], "\n".join(f.format() for f in errors)
    assert self_scan.parse_errors == 0


def test_missing_lint_root_is_an_error_not_a_clean_scan(tmp_path):
    """A typo'd root in [tool.graftlint] paths (or on the CLI) must not
    read as a clean scan of zero files."""
    result = lint_paths(["no_such_dir"], str(tmp_path))
    assert result.files == 0
    (f,) = result.findings
    assert f.rule == "JGL000" and f.severity == "error"
    assert "does not exist" in f.message


def test_self_scan_covers_the_tree(self_scan):
    # the scan actually walked the repo (a path typo in pyproject would
    # otherwise read as "clean")
    assert self_scan.files > 100
    # every committed suppression carries a reason — lint_paths counts a
    # suppression only when the reasoned pragma matched a finding
    assert self_scan.suppressed >= 3


def test_self_scan_warnings_stay_bounded(self_scan):
    """The warning ratchet, burned down to ZERO (PR 8): the tree scans
    clean at every severity — if your PR adds a warning, either fix it
    or suppress it with a reason; there is no budget to hide in."""
    warnings = [f for f in self_scan.findings if f.severity == "warning"]
    assert len(warnings) == 0, "\n".join(f.format() for f in warnings)


# ------------------------------------------------------------------- CLI


class TestRunnerCli:
    def run(self, *argv, cwd=None):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint.py"),
             *argv],
            capture_output=True, text=True, timeout=300, cwd=cwd or REPO)

    def test_json_output_and_exit_zero_on_clean_tree(self, tmp_path):
        # a small clean tree keeps this a plumbing test — the full-repo
        # scan already runs in-process via the self_scan fixture
        good = tmp_path / "improved_body_parts_tpu" / "ok.py"
        good.parent.mkdir()
        good.write_text("import json\njson.dumps({}, allow_nan=False)\n")
        proc = self.run("--root", str(tmp_path), "--format", "json",
                        "improved_body_parts_tpu")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        out = json.loads(proc.stdout)
        assert out["files"] == 1
        assert out["counts"]["error"] == 0
        assert out["version"] == GRAFTLINT_VERSION
        assert out["ruleset"] == ruleset_hash()

    def test_exit_one_on_error_findings(self, tmp_path):
        bad = tmp_path / "improved_body_parts_tpu" / "bad.py"
        bad.parent.mkdir()
        bad.write_text("import json\njson.dumps({})\n")
        proc = self.run("--root", str(tmp_path),
                        "improved_body_parts_tpu")
        assert proc.returncode == 1
        assert "JGL004" in proc.stdout

    def test_rules_listing(self):
        proc = self.run("--rules")
        assert proc.returncode == 0
        for rid in ("JGL001", "JGL007"):
            assert rid in proc.stdout

    def test_changed_mode_bad_ref_exits_two(self, tmp_path):
        # an empty repo: any ref is unresolvable, and the run must say
        # so loudly (2), never read as a clean pass (0)
        repo = tmp_path / "r"
        repo.mkdir()
        subprocess.run(["git", "init", "-q"], cwd=repo, check=True,
                       capture_output=True)
        proc = self.run("--root", str(repo), "--changed",
                        "not-a-ref-xyz")
        assert proc.returncode == 2

    def test_changed_mode_lints_only_the_diff(self, tmp_path):
        repo = tmp_path / "r"
        (repo / "improved_body_parts_tpu").mkdir(parents=True)
        (repo / "tools").mkdir()
        env = dict(os.environ,
                   GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

        def git(*argv):
            subprocess.run(["git", *argv], cwd=repo, check=True,
                           capture_output=True, env=env)

        git("init", "-q")
        clean = repo / "improved_body_parts_tpu" / "clean.py"
        clean.write_text("import json\njson.dumps({})\n")  # pre-existing
        git("add", "-A")
        git("commit", "-qm", "seed")
        # new bad file + an untracked one; the committed bad file must
        # NOT be linted in --changed mode
        (repo / "improved_body_parts_tpu" / "new.py").write_text(
            "x = 1\n")
        proc = self.run("--root", str(repo), "--changed", "HEAD",
                        "--format", "json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        out = json.loads(proc.stdout)
        assert out["files"] == 1  # only new.py; clean.py untouched
        assert out["counts"]["error"] == 0



def test_install_hook_writes_pre_push_and_refuses_foreign(tmp_path):
    """`lint.py install-hook` drops a pre-push running BOTH analysis
    tiers; idempotent over its own hook, refuses to clobber one it did
    not write."""
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True)

    def run(*argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint.py"),
             *argv, "--root", str(tmp_path)],
            capture_output=True, text=True, timeout=120)

    proc = run("install-hook")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    hook = tmp_path / ".git" / "hooks" / "pre-push"
    content = hook.read_text()
    assert "lint.py" in content and "program_audit.py" in content
    assert os.access(hook, os.X_OK)
    assert run("install-hook").returncode == 0  # idempotent
    hook.write_text("#!/bin/sh\necho custom\n")
    proc = run("install-hook")
    assert proc.returncode == 2
    assert "refusing" in proc.stderr
    assert hook.read_text() == "#!/bin/sh\necho custom\n"


def test_bench_provenance_carries_linter_stamp():
    """bench.py's provenance block stamps linter version + rule-set
    hash so lint counts are only compared between identical rule
    sets."""
    sys.path.insert(0, REPO)
    import bench

    prov = bench._provenance()
    assert prov["graftlint"]["version"] == GRAFTLINT_VERSION
    assert prov["graftlint"]["ruleset"] == ruleset_hash()
    # the program-audit tier stamps its own check-set hash (over
    # analysis/program/ only — importing it pulls no jax, so this
    # test stays on a bare interpreter)
    from improved_body_parts_tpu.analysis.program import (
        GRAFTAUDIT_VERSION,
        audit_ruleset_hash,
    )

    assert prov["graftaudit"]["version"] == GRAFTAUDIT_VERSION
    assert prov["graftaudit"]["ruleset"] == audit_ruleset_hash()


def test_scope_covers_fault_tolerant_serving_modules():
    """ISSUE 11 satellite: the new pool/policy/breaker layer lives in
    the JGL002 hot-path scope (serving threads run its code per
    request/failover) and JGL005 sees its thread/executor lifecycles —
    locked on the files' actual paths so a future move out of serve/
    can't silently drop them from the sweep."""
    hot = """
        import jax.numpy as jnp

        def failover_loop(requests):
            for r in requests:
                out = jnp.sum(r)
                route(float(out))
    """
    for path in ("improved_body_parts_tpu/serve/pool.py",
                 "improved_body_parts_tpu/serve/policy.py",
                 "improved_body_parts_tpu/serve/breaker.py"):
        assert "JGL002" in rules_of(lint(hot, path=path)), path
    leak = """
        import threading

        def fence(engine):
            t = threading.Thread(target=engine.stop)
            t.start()
    """
    assert "JGL005" in rules_of(
        lint(leak, path="improved_body_parts_tpu/serve/pool.py"))


def test_scope_covers_cascade_module():
    """ISSUE 13 satellite: the cascade layer (serve/cascade.py) lives in
    the JGL002 hot-path scope — its routing callbacks run on the
    engines' completion threads per request — and JGL005 sees any
    thread/executor lifecycle it might grow.  Locked on the file's
    actual path so a future move out of serve/ can't silently drop it
    from the sweep."""
    hot = """
        import jax.numpy as jnp

        def escalate_loop(frames):
            for f in frames:
                score = jnp.max(f)
                route(float(score))
    """
    assert "JGL002" in rules_of(
        lint(hot, path="improved_body_parts_tpu/serve/cascade.py"))
    leak = """
        import threading

        def escalate(engine):
            t = threading.Thread(target=engine.submit)
            t.start()
    """
    assert "JGL005" in rules_of(
        lint(leak, path="improved_body_parts_tpu/serve/cascade.py"))


def test_scope_covers_process_serving_modules():
    """ISSUE 16 satellite: the process-serving layer (serve/worker.py,
    serve/router.py) lives in the JGL002 hot-path scope (the worker
    serve loop and the router's submit/fetch paths run per request)
    and JGL005 sees its process/thread/pipe lifecycles — locked on the
    files' actual paths so a future move out of serve/ can't silently
    drop them from the sweep."""
    hot = """
        import jax.numpy as jnp

        def serve_loop(slots):
            for s in slots:
                out = jnp.sum(s)
                respond(float(out))
    """
    for path in ("improved_body_parts_tpu/serve/worker.py",
                 "improved_body_parts_tpu/serve/router.py"):
        assert "JGL002" in rules_of(lint(hot, path=path)), path
    leak = """
        import threading

        def spawn_fetcher(engine):
            t = threading.Thread(target=engine.fetch)
            t.start()
    """
    for path in ("improved_body_parts_tpu/serve/worker.py",
                 "improved_body_parts_tpu/serve/router.py"):
        assert "JGL005" in rules_of(lint(leak, path=path)), path


def test_scope_covers_reqtrace_and_slo_modules():
    """ISSUE 15 satellite: the per-request observability layer
    (obs/reqtrace.py, obs/slo.py) runs ON the serve threads for every
    request — node open/finish and SLO recording are hot-path code and
    live in the JGL002 scope (the rest of obs/ is scrape-time/export
    code and stays out), with JGL005 covering any thread lifecycle they
    might grow.  Locked on the files' actual paths so a future move
    can't silently drop them from the sweep."""
    hot = """
        import jax.numpy as jnp

        def record_loop(outcomes):
            for o in outcomes:
                v = jnp.max(o)
                track(float(v))
    """
    for path in ("improved_body_parts_tpu/obs/reqtrace.py",
                 "improved_body_parts_tpu/obs/slo.py"):
        assert "JGL002" in rules_of(lint(hot, path=path)), path
    # the rest of obs/ stays out of the hot-path scope
    assert "JGL002" not in rules_of(
        lint(hot, path="improved_body_parts_tpu/obs/registry.py"))
    leak = """
        import threading

        def emit(record):
            t = threading.Thread(target=record.flush)
            t.start()
    """
    assert "JGL005" in rules_of(
        lint(leak, path="improved_body_parts_tpu/obs/slo.py"))


def test_scope_covers_fleet_module():
    """ISSUE 18 satellite: worker-side telemetry (obs/fleet.py) runs ON
    the worker serve loop — publish/record between batches is hot-path
    code and lives in the JGL002 scope; JGL004 covers any JSON it
    emits and JGL005 its thread/shm lifecycles (both repo-wide).
    Locked on the file's actual path so a future move can't silently
    drop it from the sweep."""
    hot = """
        import jax.numpy as jnp

        def publish_loop(blocks):
            for b in blocks:
                v = jnp.sum(b)
                store(float(v))
    """
    assert "JGL002" in rules_of(
        lint(hot, path="improved_body_parts_tpu/obs/fleet.py"))
    bad_json = """
        import json

        def dump(report, f):
            json.dump(report, f)
    """
    assert "JGL004" in rules_of(
        lint(bad_json, path="improved_body_parts_tpu/obs/fleet.py"))
    leak = """
        import threading

        def watch(view):
            t = threading.Thread(target=view.poll)
            t.start()
    """
    assert "JGL005" in rules_of(
        lint(leak, path="improved_body_parts_tpu/obs/fleet.py"))


def test_scope_covers_history_module():
    """ISSUE 19 satellite: the telemetry-history sampler (obs/history.py)
    scrapes every registry collector at a fixed cadence while serving is
    live — a hidden host sync inside its tick would stall the same GIL
    the dispatch threads run on, so it lives in the JGL002 scope; JGL005
    sees its sampler-thread lifecycle (repo-wide).  Locked on the file's
    actual path so a future move can't silently drop it from the
    sweep."""
    hot = """
        import jax.numpy as jnp

        def fold_loop(samples):
            for s in samples:
                v = jnp.sum(s)
                ingest(float(v))
    """
    assert "JGL002" in rules_of(
        lint(hot, path="improved_body_parts_tpu/obs/history.py"))
    leak = """
        import threading

        def start_sampler(store):
            t = threading.Thread(target=store.sample_now)
            t.start()
    """
    assert "JGL005" in rules_of(
        lint(leak, path="improved_body_parts_tpu/obs/history.py"))


def test_donation_tracks_distill_factory():
    """The distill step factory is in the donating-factories config:
    JGL001 must flag a read of the state after it flowed into a
    make_distill_train_step-built step, exactly like make_train_step."""
    bad = """
        from improved_body_parts_tpu.train import make_distill_train_step

        def run(model, teacher, cfg, opt, state, tvars, batch):
            step = make_distill_train_step(model, teacher, cfg, opt)
            new_state, loss = step(state, tvars, *batch)
            return state.params  # read after donation
    """
    assert "JGL001" in rules_of(
        lint(bad, path="improved_body_parts_tpu/train/x.py"))


def test_scope_covers_partition_module():
    """ISSUE 12 satellite: the GSPMD partition module (and the rest of
    parallel/) lives in the JGL002 hot-path scope — its
    sharding/resharding helpers run on the train entry path and
    device_prefetch's producer thread runs per batch.  Locked on the
    actual paths so a future move can't silently drop them."""
    hot = """
        import jax.numpy as jnp

        def reshard_loop(leaves):
            for leaf in leaves:
                placed = jnp.asarray(leaf) * 2
                record(placed.item())
    """
    for path in ("improved_body_parts_tpu/parallel/partition.py",
                 "improved_body_parts_tpu/parallel/prefetch.py",
                 "improved_body_parts_tpu/parallel/mesh.py"):
        assert "JGL002" in rules_of(lint(hot, path=path)), path


def test_scope_covers_decode_payload_ops():
    """ISSUE 20 satellite: the decode-payload ops (ops/peaks.py and its
    config-selectable Pallas twin ops/pallas_peaks.py) are traced into
    every compact decode program on the serve dispatch path — a hidden
    readback there would serialize the program queue, so both live in
    the JGL002 hot-path scope.  Locked on the files' actual paths so a
    future move can't silently drop them; the rest of ops/ (loss/
    training code) stays out."""
    hot = """
        import jax.numpy as jnp

        def gather_loop(maps):
            rows = []
            for m in maps:
                v = jnp.max(m)
                rows.append(float(v))
            return rows
    """
    for path in ("improved_body_parts_tpu/ops/peaks.py",
                 "improved_body_parts_tpu/ops/pallas_peaks.py"):
        assert "JGL002" in rules_of(lint(hot, path=path)), path
    assert "JGL002" not in rules_of(
        lint(hot, path="improved_body_parts_tpu/ops/losses.py"))
