"""Predictor integration tests with a stub network.

A constant-output stub model isolates the Predictor's own algebra — flip
ensemble (mirror + channel permutation + average), on-device cubic upsample,
padding/unpadding, bucketing — from network weights, and a mirror-symmetric
planted person validates the full predict→decode→OKS loop.
"""
import numpy as np
import pytest

from improved_body_parts_tpu.config import (
    InferenceModelParams,
    InferenceParams,
    default_inference_params,
    get_config,
)

CFG = get_config("canonical")
SK = CFG.skeleton


class StubModel:
    """Ignores the input image; returns fixed stride-4 maps for whatever
    spatial size it is given (both flip-batch lanes see the same maps)."""

    def __init__(self, maps, skeleton=SK):
        self.maps = maps  # (h, w, C) numpy
        self.skeleton = skeleton

    def apply(self, variables, imgs, train=False):
        import jax.numpy as jnp

        n, h, w, _ = imgs.shape
        stride = self.skeleton.stride
        maps = jnp.asarray(self.maps[:h // stride, :w // stride])
        batch = jnp.broadcast_to(maps, (n, *maps.shape))
        return [[batch]]


def _stub_predictor(maps, boxsize, bucket=64, skeleton=SK):
    from improved_body_parts_tpu.infer import Predictor

    params, _ = default_inference_params()
    model_params = InferenceModelParams(boxsize=boxsize, max_downsample=64)
    return Predictor(StubModel(maps, skeleton), {}, skeleton, params,
                     model_params, bucket=bucket)


def test_flip_ensemble_algebra():
    """Output must equal (maps + perm(mirror(maps)))/2 upsampled — computed
    independently here with jax.image (the predictor's upsample method)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    h = w = 64  # image size; stride-4 maps are 16x16
    maps = rng.uniform(0, 1, (h // 4, w // 4, SK.num_layers)).astype(np.float32)
    pred = _stub_predictor(maps, boxsize=h)
    img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    heat, paf = pred.predict(img)

    mirrored = maps[:, ::-1, :]
    paf_exp = (maps[..., :SK.paf_layers]
               + mirrored[..., :SK.paf_layers][..., list(SK.flip_paf_ord)]) / 2
    heat_exp = (maps[..., SK.heat_start:]
                + mirrored[..., SK.heat_start:][..., list(SK.flip_heat_ord)]) / 2
    expect = np.concatenate([paf_exp, heat_exp], axis=-1)
    up = np.asarray(jax.image.resize(
        jnp.asarray(expect), (h, w, expect.shape[-1]), method="cubic"))

    np.testing.assert_allclose(paf, up[..., :SK.paf_layers], atol=2e-5)
    np.testing.assert_allclose(heat, up[..., SK.paf_layers:], atol=2e-5)


def test_symmetric_person_decodes_through_full_predictor():
    """Plant a mirror-symmetric person in GT maps; the flip ensemble is then
    a fixed point and the full predict→decode loop must recover the pose."""
    from improved_body_parts_tpu.data.heatmapper import Heatmapper
    from improved_body_parts_tpu.infer import decode

    h = w = 256
    sk = SK
    # build a symmetric stick person centered at w/2 on a 256px canvas:
    # mirror-symmetric joints: x_mirror = (w-1) - x with L/R swapped
    joints = np.zeros((1, sk.num_parts, 3), np.float32)
    joints[:, :, 2] = 2
    cx = (w - 1) / 2

    def put(name, dx, y):
        joints[0, sk.parts_dict[name]] = [cx + dx, y, 1]

    put("nose", 0, 40)
    put("neck", 0, 70)
    for lr, sgn in (("R", -1), ("L", 1)):
        put(lr + "sho", sgn * 30, 75)
        put(lr + "elb", sgn * 42, 110)
        put(lr + "wri", sgn * 46, 145)
        put(lr + "hip", sgn * 18, 150)
        put(lr + "kne", sgn * 20, 195)
        put(lr + "ank", sgn * 21, 240)
        put(lr + "eye", sgn * 8, 34)
        put(lr + "ear", sgn * 14, 38)

    import dataclasses

    small = dataclasses.replace(SK, width=w, height=h)
    maps = Heatmapper(small).create_heatmaps(
        joints, np.ones(small.grid_shape, np.float32))

    pred = _stub_predictor(maps.astype(np.float32), boxsize=h)
    img = np.zeros((h, w, 3), np.uint8)
    heat, paf = pred.predict(img)
    # a perfectly symmetric person is a fixed point of the flip ensemble but
    # leaves exact midline/plateau ties; break them AFTER the ensemble (a
    # real network never ties exactly)
    rng = np.random.default_rng(1)
    heat = heat + rng.uniform(0, 1e-6, heat.shape)
    params, _ = default_inference_params()
    results = decode(heat.astype(np.float32), paf.astype(np.float32),
                     params, sk)
    assert len(results) == 1
    coords, score = results[0]
    nose = coords[0]  # COCO part 0 = nose
    assert nose is not None
    assert abs(nose[0] - cx) < 4 and abs(nose[1] - 40) < 4


def test_fast_path_matches_regular_decode():
    """predict_fast (on-device NMS, scaled-resolution decode + coordinate
    rescale) must land the same person within a couple of pixels of the
    regular path."""
    import dataclasses

    from improved_body_parts_tpu.data.heatmapper import Heatmapper
    from improved_body_parts_tpu.infer import decode

    import sys

    sys.path.insert(0, "tests")
    from test_decode import synth_person_joints

    h = w = 256
    rng = np.random.default_rng(3)
    joints = synth_person_joints(70, 40, 180).astype(np.float32)
    small = dataclasses.replace(SK, width=w, height=h)
    maps = Heatmapper(small).create_heatmaps(
        joints, np.ones(small.grid_shape, np.float32))
    maps = (maps + rng.uniform(0, 1e-6, maps.shape)).astype(np.float32)

    pred = _stub_predictor(maps, boxsize=h)
    img = np.zeros((h, w, 3), np.uint8)
    params, _ = default_inference_params()

    heat, paf = pred.predict(img)
    regular = decode(heat.astype(np.float32), paf.astype(np.float32),
                     params, SK)
    fh, fp, mask, scale = pred.predict_fast(img)
    assert mask.dtype == bool and mask.shape[:2] == fh.shape[:2]
    fast = decode(fh, fp, params, SK, peak_mask=mask, coord_scale=scale)

    # the invariant: the fast path reproduces the regular path (synthetic
    # upsampled GT can split plateau peaks — both paths must agree on it)
    assert len(regular) == len(fast) >= 1
    best_r = max(regular, key=lambda r: r[1])
    best_f = max(fast, key=lambda r: r[1])
    matched = 0
    for pa, pb in zip(best_r[0], best_f[0]):
        if pa is None or pb is None or pa == (0.0, 0.0) or pb == (0.0, 0.0):
            continue
        assert abs(pa[0] - pb[0]) < 2.5 and abs(pa[1] - pb[1]) < 2.5, (pa, pb)
        matched += 1
    assert matched >= 10


def test_fast_path_rejects_multi_scale_grid():
    from improved_body_parts_tpu.config import InferenceParams
    from improved_body_parts_tpu.infer import Predictor

    rng = np.random.default_rng(0)
    maps = rng.uniform(0, 1, (16, 16, SK.num_layers)).astype(np.float32)
    params = InferenceParams(scale_search=(0.5, 1.0))
    model_params = InferenceModelParams(boxsize=64)
    pred = Predictor(StubModel(maps), {}, SK, params, model_params, bucket=64)
    with pytest.raises(ValueError, match="single-entry"):
        pred.predict_fast(np.zeros((64, 64, 3), np.uint8))


class ImageFollowingStub:
    """Every map channel mirrors the stride-4-downsampled green channel of
    the input, so map content tracks the image through the rotation grid —
    a constant stub cannot exercise the rotate → forward → rotate-back path
    (reference: evaluate.py:89-90, 108-112, 139-161)."""

    def apply(self, variables, imgs, train=False):
        import jax.numpy as jnp

        n, h, w, _ = imgs.shape
        g = imgs[..., 1]
        g4 = g.reshape(n, h // SK.stride, SK.stride,
                       w // SK.stride, SK.stride).mean(axis=(2, 4))
        maps = jnp.repeat(g4[..., None], SK.num_layers, axis=-1)
        return [[maps]]


@pytest.mark.parametrize("shape", [(256, 256), (192, 256)])
def test_rotation_grid_returns_maps_to_original_orientation(shape):
    """With rotation_search=(0, ±40), each rotated pass must be warped back
    so the averaged maps peak where the (unrotated) image feature is; a bug
    in the inverse warp would smear the peak along the rotation arc."""
    from improved_body_parts_tpu.infer import Predictor

    h, w = shape
    x0, y0 = int(w * 0.64), int(h * 0.33)  # within the rotation footprint
    # a Gaussian blob, not a filled disc: cubic upsampling overshoots at
    # plateau edges, which would move the argmax off the planted centre
    yy, xx = np.mgrid[:h, :w]
    g = np.exp(-((xx - x0) ** 2 + (yy - y0) ** 2) / (2 * 6.0 ** 2))
    img = np.zeros((h, w, 3), np.uint8)
    img[..., 1] = (255 * g).astype(np.uint8)

    params = InferenceParams(scale_search=(1.0,),
                             rotation_search=(0.0, 40.0, -40.0))
    model_params = InferenceModelParams(boxsize=h, max_downsample=64)
    pred = Predictor(ImageFollowingStub(), {}, SK, params, model_params,
                     bucket=64)
    heat, paf = pred.predict(img)
    assert heat.shape == (h, w, SK.heat_layers + 2)

    py, px = np.unravel_index(np.argmax(heat[..., 0]), (h, w))
    assert abs(px - x0) <= 3 and abs(py - y0) <= 3, (px, py, x0, y0)

    # every grid entry saw the blob, so the rotation passes must contribute
    # comparable mass at the blob — not just the angle-0 pass
    no_rot = Predictor(ImageFollowingStub(), {}, SK,
                       InferenceParams(scale_search=(1.0,)),
                       model_params, bucket=64)
    heat0, _ = no_rot.predict(img)
    peak = heat[py, px, 0]
    assert peak > 0.6 * heat0[..., 0].max(), (peak, heat0[..., 0].max())


@pytest.mark.parametrize("shape,angle", [((96, 96), 25.0), ((64, 96), -40.0),
                                         ((96, 64), 33.0)])
def test_warp_rotate_matches_cv2(shape, angle):
    """The on-device rotation lane must reproduce the host path's
    cv2.warpAffine(getRotationMatrix2D(...)) semantics — including the
    y-down angle direction and the default inverse mapping — up to cv2's
    5-bit fixed-point coordinate quantization (smooth test field keeps
    that error tiny)."""
    import cv2
    import jax.numpy as jnp

    from improved_body_parts_tpu.infer.predict import _warp_rotate

    h, w = shape
    yy, xx = np.mgrid[:h, :w].astype(np.float32)
    field = np.stack([
        np.sin(xx / 9.0) * np.cos(yy / 7.0),
        np.exp(-((xx - w * 0.6) ** 2 + (yy - h * 0.4) ** 2) / (2 * 8.0 ** 2)),
    ], axis=-1).astype(np.float32)

    # the reference's center quirk: rc = (h/2, w/2) passed as (x, y)
    center = (h / 2, w / 2)
    M = cv2.getRotationMatrix2D(center, angle, 1)
    want = cv2.warpAffine(field, M, (w, h))
    got = np.asarray(_warp_rotate(jnp.asarray(field), angle, center))
    # worst-case tolerance covers cv2's fixed-point rounding at the
    # zero-border edge; the mean bound pins agreement everywhere else
    np.testing.assert_allclose(got, want, atol=2e-2)
    assert np.abs(got - want).mean() < 5e-4


def test_compact_ms_rotation_grid_matches_host_predict():
    """The device-resident rotation ensemble (predict_compact_ms with
    rotation_search != (0,)) must produce the same averaged maps as the
    host path (Predictor.predict, which runs the grid through cv2) and a
    peak payload equal to host NMS on those maps — the round-3 verdict's
    rotation-completeness item."""
    import jax
    import jax.numpy as jnp

    from improved_body_parts_tpu.infer import Predictor
    from improved_body_parts_tpu.ops.nms import peak_mask_np

    h = w = 128
    x0, y0 = int(w * 0.62), int(h * 0.38)
    yy, xx = np.mgrid[:h, :w]
    g = np.exp(-((xx - x0) ** 2 + (yy - y0) ** 2) / (2 * 6.0 ** 2))
    img = np.zeros((h, w, 3), np.uint8)
    img[..., 1] = (255 * g).astype(np.uint8)

    params = InferenceParams(scale_search=(1.0,),
                             rotation_search=(0.0, 30.0, -30.0))
    model_params = InferenceModelParams(boxsize=h, max_downsample=64)
    pred = Predictor(ImageFollowingStub(), {}, SK, params, model_params,
                     bucket=64)

    # host path: cv2 rotations, averaged at original resolution
    heat_host, paf_host = pred.predict(img)
    host_maps = np.concatenate([paf_host, heat_host], axis=-1)

    # device path: scale 1 → the decode grid IS the original resolution,
    # so the averaged device maps are directly comparable
    res = pred.predict_compact_ms(img, params=params)
    assert res.image_size == h and res.coord_scale == (1.0, 1.0)
    prepared, (rh, rw) = pred._prepare_input(img, 1.0)
    dev_maps = np.mean([
        np.asarray(pred._scale_to_grid_fn(prepared.shape[:2], (rh, rw),
                                          (rh, rw), angle)(
            pred.variables, jnp.asarray(prepared)))
        for angle in params.rotation_search], axis=0)
    # tolerance covers cv2's warp-on-uint8 rounding + 5-bit fixed point
    np.testing.assert_allclose(dev_maps, host_maps, atol=2e-2)

    # payload peaks == host NMS on the device-averaged maps
    kp = np.ascontiguousarray(
        dev_maps[..., SK.paf_layers:SK.paf_layers + SK.num_parts])
    host_mask = peak_mask_np(kp, thre=params.thre1)
    for c in range(SK.num_parts):
        ys, xs = np.nonzero(host_mask[..., c])
        slots = np.nonzero(res.peaks.valid[c])[0]
        dev = set(zip(res.peaks.xs[c, slots].tolist(),
                      res.peaks.ys[c, slots].tolist()))
        assert dev == set(zip(xs.tolist(), ys.tolist())), f"channel {c}"


def test_pipelined_inference_matches_sequential():
    """pipelined_inference (forward N+1 overlaps decode N, threaded decode)
    must yield exactly the sequential predict_fast→decode results, in input
    order — including across images of different sizes."""
    import dataclasses

    from improved_body_parts_tpu.data.heatmapper import Heatmapper
    from improved_body_parts_tpu.infer import decode, pipelined_inference

    import sys

    sys.path.insert(0, "tests")
    from test_decode import synth_person_joints

    h = w = 256
    rng = np.random.default_rng(4)
    joints = synth_person_joints(70, 40, 180).astype(np.float32)
    small = dataclasses.replace(SK, width=w, height=h)
    maps = Heatmapper(small).create_heatmaps(
        joints, np.ones(small.grid_shape, np.float32))
    maps = (maps + rng.uniform(0, 1e-6, maps.shape)).astype(np.float32)

    pred = _stub_predictor(maps, boxsize=h)
    params, _ = default_inference_params()
    # different sizes exercise ordering (different buckets + coord scales)
    images = [np.zeros((h, w, 3), np.uint8),
              np.zeros((192, 256, 3), np.uint8),
              np.zeros((h, w, 3), np.uint8),
              np.zeros((h, w, 3), np.uint8)]

    sequential = []
    for img in images:
        fh, fp, mask, scale = pred.predict_fast(img)
        sequential.append(decode(fh, fp, params, SK, peak_mask=mask,
                                 coord_scale=scale))

    piped = list(pipelined_inference(pred, images, decode_workers=2))
    assert len(piped) == len(sequential) == 4
    for seq, pipe in zip(sequential, piped):
        assert len(seq) == len(pipe)
        for (ca, sa), (cb, sb) in zip(seq, pipe):
            assert sa == pytest.approx(sb, abs=1e-6)
            assert ca == cb


def test_spatially_sharded_predictor_matches_single_device(eight_devices):
    """A ('data','model') mesh spreads one image's ensemble across devices
    (flip lanes over 'data', height over 'model' with GSPMD conv halos);
    the maps must match the single-device predictor."""
    import jax

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.infer import Predictor
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.parallel import make_mesh

    cfg = get_config("tiny")
    import jax.numpy as jnp

    model = build_model(cfg, dtype=jnp.float32)
    img0 = jnp.zeros((1, 128, 128, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), img0, train=False)

    params = InferenceParams(scale_search=(1.0,))
    mp = InferenceModelParams(boxsize=128, max_downsample=64)
    plain = Predictor(model, variables, SK, params, mp, bucket=64)
    sharded = Predictor(model, variables, SK, params, mp, bucket=64,
                        mesh=make_mesh(data=2, model=4))

    rng = np.random.default_rng(5)
    img = rng.integers(0, 255, (128, 128, 3), dtype=np.uint8)
    heat_a, paf_a = plain.predict(img)
    heat_b, paf_b = sharded.predict(img)
    np.testing.assert_allclose(heat_b, heat_a, atol=3e-5)
    np.testing.assert_allclose(paf_b, paf_a, atol=3e-5)


def test_bucketing_reuses_programs():
    rng = np.random.default_rng(2)
    maps = rng.uniform(0, 1, (64, 64, SK.num_layers)).astype(np.float32)
    pred = _stub_predictor(maps, boxsize=100, bucket=64)
    for shape in [(100, 130), (90, 120), (100, 100)]:
        img = rng.integers(0, 255, (*shape, 3), dtype=np.uint8)
        heat, paf = pred.predict(img)
        assert heat.shape[:2] == shape
    assert len(pred._fns) <= 2  # shapes collapse into at most 2 buckets
