"""Device decode parity suite (``ops.assembly`` + the fused decode path).

The contract under test: the fused device decode — forward + compact
extraction + greedy assembly in ONE jitted program
(``Predictor.predict_decoded*``) — must reproduce the host decoder
(``decode_compact``'s per-limb walk + ``find_people`` assembly) person
for person and keypoint for keypoint, on synthetic fixtures AND
COCO-shaped multi-person samples, including the exactly score-tied
mirror-ghost class (PR 2's flip-TTA finding); and every overflow class
must degrade to the documented host fallback, never fail or drop
people silently.

Documented tolerance: the kernel accumulates person scores in fp32
where the host uses float64 — raw candidate scores/coordinates are
identical, so comparisons are at 1e-3/1e-4, not bit-exact.
"""
import dataclasses
import sys

import numpy as np
import pytest

from improved_body_parts_tpu.config import default_inference_params, get_config

sys.path.insert(0, "tests")
from test_decode import synth_person_joints  # noqa: E402
from test_predictor import _stub_predictor  # noqa: E402

CFG = get_config("canonical")
SK = CFG.skeleton
PARAMS, _ = default_inference_params()
LIMBS_FROM = tuple(a for a, _ in SK.limbs_conn)
LIMBS_TO = tuple(b for _, b in SK.limbs_conn)


def _assemble_device(pk, cd, p_max=64, params=PARAMS):
    import jax.numpy as jnp

    from improved_body_parts_tpu.ops.assembly import greedy_assemble
    from improved_body_parts_tpu.ops.peaks import LimbCandidates, TopKPeaks

    res = greedy_assemble(
        TopKPeaks(*[jnp.asarray(a) for a in pk]),
        LimbCandidates(*[jnp.asarray(a) for a in cd]),
        limbs_from=LIMBS_FROM, limbs_to=LIMBS_TO,
        num_parts=SK.num_parts, p_max=p_max, len_rate=params.len_rate,
        connection_tole=params.connection_tole,
        remove_recon=params.remove_recon, min_parts=params.min_parts,
        min_mean_score=params.min_mean_score)
    return type(res)(*[np.asarray(a) for a in res])


def _kernel_keypoints(pk, res):
    from improved_body_parts_tpu.infer.decode import subsets_to_keypoints

    candidate = np.stack(
        [pk.x_ref.ravel().astype(np.float64),
         pk.y_ref.ravel().astype(np.float64),
         pk.score.ravel().astype(np.float64),
         np.arange(pk.score.size, dtype=np.float64)], axis=1)
    return subsets_to_keypoints(res.subset[res.mask].astype(np.float64),
                                candidate, SK)


def _canon(results, digits=3):
    """Order-free canonical form: (rounded score, rounded keypoints)."""
    out = []
    for kps, s in results:
        out.append((round(float(s), 4),
                    tuple((round(p[0], digits), round(p[1], digits))
                          if p is not None else None for p in kps)))
    return sorted(out)


def _assert_same_people(got, want, tol=1e-3, pair=None, score_tol=1e-4):
    assert len(got) == len(want)
    if pair is not None:
        got, want = pair(got), pair(want)
    for (gk, gs), (wk, ws) in zip(got, want):
        assert gs == pytest.approx(ws, abs=score_tol)
        for pg, pw in zip(gk, wk):
            assert (pg is None) == (pw is None)
            if pg is not None:
                assert pg[0] == pytest.approx(pw[0], abs=tol)
                assert pg[1] == pytest.approx(pw[1], abs=tol)


def _rand_records(rng, k=8, m=16):
    """Random peak/candidate records shaped like a real compact payload:
    per-channel unique integer coords (no row-major order ties), valid
    slots arbitrary, candidates referencing only valid peaks in
    rank-descending prior order with prefix validity — exactly what
    ``limb_topk_candidates`` ships."""
    from improved_body_parts_tpu.ops.peaks import LimbCandidates, TopKPeaks

    c = SK.num_parts
    n_limbs = len(SK.limbs_conn)
    counts = rng.integers(0, k + 1, c).astype(np.int32)
    valid = np.zeros((c, k), bool)
    for ch in range(c):
        valid[ch, rng.permutation(k)[:counts[ch]]] = True
    xs = rng.integers(0, 200, (c, k)).astype(np.int32)
    ys = rng.integers(0, 200, (c, k)).astype(np.int32)
    for ch in range(c):
        seen = set()
        for s in range(k):
            while (int(ys[ch, s]), int(xs[ch, s])) in seen:
                xs[ch, s] = rng.integers(0, 200)
            seen.add((int(ys[ch, s]), int(xs[ch, s])))
    x_ref = (xs + rng.uniform(-.4, .4, (c, k))).astype(np.float32)
    y_ref = (ys + rng.uniform(-.4, .4, (c, k))).astype(np.float32)
    score = rng.uniform(0.1, 1.0, (c, k)).astype(np.float32)
    pk = TopKPeaks(xs, ys, x_ref, y_ref, score, valid, counts)

    slot_a = np.zeros((n_limbs, m), np.int32)
    slot_b = np.zeros((n_limbs, m), np.int32)
    prior = np.zeros((n_limbs, m), np.float32)
    norm = np.zeros((n_limbs, m), np.float32)
    cvalid = np.zeros((n_limbs, m), bool)
    ccount = np.zeros((n_limbs,), np.int32)
    for li, (ia, ib) in enumerate(SK.limbs_conn):
        pairs = [(a, b) for a in np.nonzero(valid[ia])[0]
                 for b in np.nonzero(valid[ib])[0]]
        rng.shuffle(pairs)
        n = min(len(pairs), int(rng.integers(0, m + 1)))
        pr = np.sort(rng.uniform(0.05, 2.0, n).astype(np.float32))[::-1]
        for i, (a, b) in enumerate(pairs[:n]):
            slot_a[li, i], slot_b[li, i] = a, b
            prior[li, i] = pr[i]
            norm[li, i] = np.float32(np.hypot(
                x_ref[ia, a] - x_ref[ib, b], y_ref[ia, a] - y_ref[ib, b]))
        cvalid[li, :n] = True
        ccount[li] = n
    return pk, LimbCandidates(slot_a, slot_b, prior, norm, cvalid, ccount)


def _host_from_records(pk, cd):
    from improved_body_parts_tpu.infer.decode import (
        CompactResult,
        decode_compact,
    )

    comp = CompactResult(peaks=pk, stats=cd, image_size=200,
                        coord_scale=(1.0, 1.0))
    return decode_compact(comp, PARAMS, SK, use_native=False)


# ------------------------------------------------------ kernel-level parity


def test_greedy_assemble_matches_host_randomized():
    """The kernel vs the host walk+assembly on randomized candidate
    sets — crowded enough to exercise spawn, assign, replace, rescore,
    the disjoint merge and the prune, across 20 seeds."""
    rng = np.random.default_rng(0)
    for _ in range(20):
        pk, cd = _rand_records(rng)
        want = _canon(_host_from_records(pk, cd))
        res = _assemble_device(pk, cd)
        assert not (res.peak_overflow or res.cand_overflow
                    or res.person_overflow)
        got = _canon(_kernel_keypoints(pk, res))
        assert got == want


def test_overflow_flags_not_exceptions():
    """Each capacity condition sets its flag; the program never raises
    (an XLA program cannot) and the table never grows past p_max."""
    rng = np.random.default_rng(1)
    pk, cd = _rand_records(rng)
    # true counts past capacity: the host raises CompactOverflow, the
    # kernel flags
    pk_of = pk._replace(count=pk.count + pk.valid.shape[1])
    res = _assemble_device(pk_of, cd)
    assert res.peak_overflow and not res.cand_overflow
    cd_of = cd._replace(count=cd.count + cd.valid.shape[1])
    res = _assemble_device(pk, cd_of)
    assert res.cand_overflow and not res.peak_overflow
    # person table capacity 1: crowded records must flag, and the mask
    # can never exceed the capacity
    res = _assemble_device(pk, cd, p_max=1)
    assert res.mask.sum() <= 1
    assert res.person_overflow or _host_from_records(pk, cd) == []


def test_pallas_candidate_walk_parity_interpret():
    """The Pallas sketch of the inner candidate walk (gated behind
    tools/pallas_check.py --assembly) agrees with the host reference
    walk in interpreter mode on CPU."""
    from improved_body_parts_tpu.ops.pallas_assembly import (
        walk_parity_benchmark,
    )

    r = walk_parity_benchmark(n_limbs=8, m_cap=32, k=16, trials=3,
                              iters=1, interpret=True)
    assert r["parity_ok"]


# ------------------------------------------------- fused-program parity


def _crowd_predictor(people, h, w=None, seed=7):
    from improved_body_parts_tpu.data.heatmapper import Heatmapper

    w = w or h
    rng = np.random.default_rng(seed)
    small = dataclasses.replace(SK, width=w, height=h)
    joints = np.concatenate(people, axis=0).astype(np.float32)
    maps = Heatmapper(small).create_heatmaps(
        joints, np.ones(small.grid_shape, np.float32))
    maps = (maps + rng.uniform(0, 1e-6, maps.shape)).astype(np.float32)
    return _stub_predictor(maps, boxsize=h), np.zeros((h, w, 3), np.uint8)


@pytest.fixture(scope="module")
def planted_pair():
    """Two planted people on a 256px canvas + the host reference."""
    from improved_body_parts_tpu.infer import decode_compact

    pred, img = _crowd_predictor(
        [synth_person_joints(70, 40, 180),
         synth_person_joints(160, 60, 150)], h=256)
    host = decode_compact(pred.predict_compact(img), PARAMS, SK,
                          use_native=False)
    return pred, img, host


def test_device_decode_matches_host_on_planted_people(planted_pair):
    from improved_body_parts_tpu.infer import decode_device

    pred, img, host = planted_pair
    dev = pred.predict_decoded(img)
    assert dev.ok and dev.n_people == len(host)
    _assert_same_people(decode_device(dev, SK), host)


def test_device_decode_batch_matches_single(planted_pair):
    from improved_body_parts_tpu.infer import decode_device

    pred, img, host = planted_pair
    for dev in pred.predict_decoded_batch([img, img]):
        assert dev.ok
        _assert_same_people(decode_device(dev, SK), host)


def test_pipelined_device_decode_matches_host(planted_pair):
    from improved_body_parts_tpu.infer import pipelined_inference

    pred, img, host = planted_pair
    out = list(pipelined_inference(pred, [img] * 3, PARAMS, SK,
                                   use_native=False, device_decode=True))
    assert len(out) == 3
    for res in out:
        _assert_same_people(res, host)


def test_device_decode_matches_host_on_coco_shaped_crowd():
    """COCO-shaped sample: a non-square canvas (480x640, the modal COCO
    size) with four people at mixed scales and an overlapping pair —
    the workload where the merge/replace rules actually fire.  Device
    fused decode vs decode_compact (exact walk order) AND vs the
    full-map fast path (position-paired, loose score tolerance: on
    crowds the compact candidate ranking — fp32 device rank order vs
    the host's float64 row-major stable sort, documented in
    ops/peaks.py — legitimately selects a different contested
    connection; the HOST compact path deviates from the full path by
    the same ~1% on this fixture, so the tight comparison is against
    decode_compact)."""
    from improved_body_parts_tpu.infer import (
        decode,
        decode_compact,
        decode_device,
    )

    pred, img = _crowd_predictor(
        [synth_person_joints(60, 60, 260),
         synth_person_joints(300, 100, 200),
         synth_person_joints(430, 160, 150),
         synth_person_joints(340, 120, 180)],  # overlaps person 2
        h=480, w=640)
    host = decode_compact(pred.predict_compact(img), PARAMS, SK,
                          use_native=False)
    dev = pred.predict_decoded(img)
    assert dev.ok
    got = decode_device(dev, SK)
    assert len(got) >= 3  # the crowd decodes (ghosts may add more)
    _assert_same_people(got, host)

    heat, paf, mask, scale = pred.predict_fast(img)
    full = decode(heat, paf, PARAMS, SK, peak_mask=mask,
                  coord_scale=scale, use_native=False)

    # structural check vs the full path: same person count, and the
    # flattened keypoint sets overlap >= 90% (person-assignment-free —
    # a contested connection may attach a part to a different person
    # or select a different tied peak: the documented compact ranking
    # deviation; the exact comparison above is against decode_compact)
    def kp_list(results):
        return [p for kps, _ in results for p in kps
                if p is not None and p != (0.0, 0.0)]

    assert len(got) == len(full)
    g_kps, f_kps = kp_list(got), kp_list(full)
    matched = sum(
        1 for pg in g_kps
        if any(abs(pg[0] - pf[0]) < 1.0 and abs(pg[1] - pf[1]) < 1.0
               for pf in f_kps))
    assert matched >= 0.9 * max(len(g_kps), len(f_kps)), \
        (matched, len(g_kps), len(f_kps))


def test_score_tie_mirror_ghosts_identical_order():
    """The flip-TTA mirror-ghost class (PR 2): a constant-output stub
    makes the merged maps exactly L/R symmetric, so every person
    decodes with an EXACTLY score-tied mirror ghost.  The fused device
    decode consumes the same device-ranked candidates as
    decode_compact, so — unlike the host fast path, which breaks the
    tie differently — the two must agree person-by-person WITHOUT any
    position pairing."""
    from improved_body_parts_tpu.infer import decode_compact, decode_device

    pred, img = _crowd_predictor([synth_person_joints(60, 40, 180)],
                                 h=256)
    host = decode_compact(pred.predict_compact(img), PARAMS, SK,
                          use_native=False)
    assert len(host) >= 2  # the person and its score-tied ghost
    dev = pred.predict_decoded(img)
    assert dev.ok
    _assert_same_people(decode_device(dev, SK), host)


# ------------------------------------------------- overflow -> fallback


def test_person_overflow_falls_back_to_host_assembly(planted_pair):
    from improved_body_parts_tpu.infer import device_decode_fn

    pred, img, host = planted_pair
    tight, _ = _crowd_predictor(
        [synth_person_joints(70, 40, 180),
         synth_person_joints(160, 60, 150)], h=256)
    tight.assembly_pmax = 1
    dev = tight.predict_decoded(img)
    assert dev.person_overflow and not dev.ok
    assert not (dev.peak_overflow or dev.cand_overflow)
    # the fallback decodes from the compact records shipped in the SAME
    # buffer — host assembly is unbounded, so the result matches
    decode_one = device_decode_fn(tight, PARAMS, SK, use_native=False)
    _assert_same_people(decode_one(dev, img), host)


def test_peak_overflow_falls_back_to_full_maps():
    from improved_body_parts_tpu.infer import decode, device_decode_fn

    pred, img = _crowd_predictor(
        [synth_person_joints(70, 40, 180),
         synth_person_joints(160, 60, 150)], h=256)
    pred.compact_topk = 1
    dev = pred.predict_decoded(img)
    assert dev.peak_overflow and not dev.ok
    heat, paf, mask, scale = pred.predict_fast(img)
    want = decode(heat, paf, PARAMS, SK, peak_mask=mask,
                  coord_scale=scale, use_native=False)
    decode_one = device_decode_fn(pred, PARAMS, SK, use_native=False)
    got = decode_one(dev, img)
    assert len(got) == len(want)


def test_device_decode_grid_route_matches_compact_ms(planted_pair):
    """Non-trivial scale grids route through the device-resident ms
    path with the assembly on the averaged maps — same contract as
    predict_compact_ms."""
    from improved_body_parts_tpu.infer import decode_compact, decode_device

    pred, img, _ = planted_pair
    ms = dataclasses.replace(PARAMS, scale_search=(0.75, 1.0))
    host = decode_compact(pred.predict_compact(img, params=ms), ms, SK,
                          use_native=False)
    dev = pred.predict_decoded(img, params=ms)
    assert dev.ok
    _assert_same_people(decode_device(dev, SK), host)
