"""ISSUE 19: the telemetry-history layer — bounded multi-resolution
retention with explicit gap accounting, strict-JSON shard persistence
with bit-identical offline replay, the derived control-plane signal
feed, the measured capacity model fitted from it, the ``/history`` +
``/query`` routes, and the report tool's completeness verifier.

Everything time-driven runs on an injected clock: the fold path is
purely (t, v)-driven by design (that is what makes replay exact), so
the tests drive it deterministically instead of sleeping.
"""
import json
import os
import re
import threading
import urllib.error
import urllib.request

import pytest

from improved_body_parts_tpu.obs import MetricsServer, Registry
from improved_body_parts_tpu.obs.events import read_events, strict_dumps
from improved_body_parts_tpu.obs.history import (
    HistoryStore,
    discover_history_shards,
    history_path_for,
    series_key,
)
from improved_body_parts_tpu.serve.capacity import CapacityModel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _store(reg=None, clock=None, **kw):
    kw.setdefault("cadence_s", 0.25)
    return HistoryStore(reg, clock=clock or FakeClock(), **kw)


def _tick(store, clock, reg_updates=(), dt=0.25):
    for fn in reg_updates:
        fn()
    clock.advance(dt)
    return store.sample_now()


class TestFoldAndRetention:
    def test_raw_ring_is_bounded(self):
        clock = FakeClock()
        reg = Registry()
        g = reg.gauge("depth")
        st = _store(reg, clock, raw_capacity=8)
        for i in range(20):
            g.set(float(i))
            _tick(st, clock)
        q = st.query("depth")
        assert len(q["points"]) == 8
        # newest points survive, oldest fall off
        assert q["points"][-1][1] == 19.0
        assert q["points"][0][1] == 12.0

    def test_aggregate_buckets_minmax_sum_count_last(self):
        clock = FakeClock()
        reg = Registry()
        g = reg.gauge("v")
        st = _store(reg, clock, levels=((2.0, 16),))
        # 4 ticks at t=0.25..1.0 all land in bucket [0,2): 3, 1, 7, 5
        for v in (3.0, 1.0, 7.0, 5.0):
            g.set(v)
            _tick(st, clock)
        q = st.query("v", step=2.0)
        assert q["step"] == 2.0
        b = q["points"][-1]
        assert (b["min"], b["max"], b["sum"], b["count"], b["last"]) \
            == (1.0, 7.0, 16.0, 4, 5.0)

    def test_open_bucket_is_visible_and_freezes_on_boundary(self):
        clock = FakeClock()
        reg = Registry()
        g = reg.gauge("v")
        st = _store(reg, clock, levels=((5.0, 16),))
        g.set(2.0)
        _tick(st, clock)               # t=0.25, bucket [0,5) open
        assert len(st.query("v", step=5.0)["points"]) == 1
        g.set(9.0)
        _tick(st, clock, dt=5.0)       # t=5.25 → [0,5) frozen, new open
        pts = st.query("v", step=5.0)["points"]
        assert len(pts) == 2
        assert pts[0]["last"] == 2.0 and pts[1]["last"] == 9.0

    def test_query_is_bounded_and_truncation_flagged(self):
        clock = FakeClock()
        reg = Registry()
        g = reg.gauge("v")
        st = _store(reg, clock)
        for i in range(10):
            g.set(float(i))
            _tick(st, clock)
        q = st.query("v", limit=3)
        assert q["truncated"] is True
        assert [p[1] for p in q["points"]] == [7.0, 8.0, 9.0]
        # since= filters from the left on the same t axis
        q2 = st.query("v", since=st.latest("v")[0] - 0.3)
        assert len(q2["points"]) == 2

    def test_unknown_series_raises_keyerror(self):
        st = _store()
        with pytest.raises(KeyError):
            st.query("nope")

    def test_max_series_bound_drops_loudly(self):
        clock = FakeClock()
        st = _store(None, clock, max_series=2,
                    sources=[lambda: [(f"g{i}", {}, "gauge", 1.0)
                                      for i in range(5)]])
        _tick(st, clock)
        assert len(st.keys()) == 2
        assert st.doc()["series_dropped"] == 3

    def test_series_key_matches_snapshot_key_format(self):
        reg = Registry()
        reg.counter("x_total", labels={"b": "2", "a": "1"}).inc()
        snap_keys = set(reg.snapshot())
        assert series_key("x_total", {"a": "1", "b": "2"}) in snap_keys


class TestGaps:
    def test_gap_detected_marked_never_interpolated(self):
        clock = FakeClock()
        reg = Registry()
        c = reg.counter("n_total")
        st = _store(reg, clock)        # cadence 0.25, gap_factor 2.5
        c.inc()
        _tick(st, clock)
        c.inc()
        _tick(st, clock)
        c.inc(3)
        _tick(st, clock, dt=2.0)       # 2.0 > 0.625 → blackout
        doc = st.doc()["gaps"]
        assert doc["count"] == 1
        g = doc["recent"][0]
        assert g["missed"] == 7        # int(2.0 / 0.25) - 1
        # the raw ring holds only REAL samples — nothing was invented
        assert len(st.query(series_key("n_total"))["points"]) == 3
        # and the rate stream marks the interval that bridges it
        rs = st.rate_series(series_key("n_total"))
        assert [gap for _, _, _, gap in rs] == [False, True]

    def test_sub_threshold_spacing_is_not_a_gap(self):
        clock = FakeClock()
        reg = Registry()
        reg.gauge("v").set(1.0)
        st = _store(reg, clock)
        for _ in range(4):
            _tick(st, clock, dt=0.5)   # 2x cadence < 2.5x threshold
        assert st.doc()["gaps"]["count"] == 0


class TestDerivedSignals:
    def test_rate_endpoint_difference_and_unknown_is_none(self):
        clock = FakeClock()
        reg = Registry()
        c = reg.counter("done_total")
        st = _store(reg, clock)
        _tick(st, clock)
        assert st.rate(series_key("done_total"), 10.0) is None  # 1 point
        for _ in range(4):
            c.inc(5)
            _tick(st, clock)
        # 20 increments over 1.0 s of ticks
        assert st.rate(series_key("done_total"), 10.0) == pytest.approx(20.0)
        assert st.rate("absent", 10.0) is None

    def test_integrate_rate_telescopes_to_counter_delta(self):
        clock = FakeClock()
        reg = Registry()
        c = reg.counter("done_total")
        st = _store(reg, clock)
        _tick(st, clock)
        for inc in (1, 4, 2, 8):
            c.inc(inc)
            _tick(st, clock)
        assert st.integrate_rate(series_key("done_total")) \
            == pytest.approx(15.0, abs=1e-9)

    def test_trend_recovers_a_linear_slope(self):
        clock = FakeClock()
        reg = Registry()
        g = reg.gauge("v")
        st = _store(reg, clock)
        for i in range(8):
            g.set(3.0 * clock.t + 1.0)
            _tick(st, clock)
        # set() used pre-advance t; slope of v = 3(t - 0.25) + 1 is 3
        assert st.trend("v", 10.0) == pytest.approx(3.0)

    def test_window_quantiles_match_percentile_meter(self):
        from improved_body_parts_tpu.utils.meters import PercentileMeter

        clock = FakeClock()
        reg = Registry()
        g = reg.gauge("v")
        st = _store(reg, clock)
        vals = [5.0, 1.0, 9.0, 3.0, 7.0, 2.0]
        pm = PercentileMeter()
        for v in vals:
            g.set(v)
            pm.update(v)
            _tick(st, clock)
        wq = st.window_quantiles("v", 100.0)
        for q, k in ((50.0, "p50"), (95.0, "p95"), (99.0, "p99")):
            assert wq["p%g" % q] == pytest.approx(pm.percentile(q))

    def test_signals_feed_and_prefix_fallback(self):
        """The control-plane feed carries the ROADMAP item 1 inputs, and
        scans by family SUFFIX: a pool/router deployment (pool_* and
        pool_engine_* families, no serve_*) feeds the same signals."""
        clock = FakeClock()
        st = _store(None, clock, sources=[lambda: [
            ("pool_queue_depth", {}, "gauge", 3.0),
            ("pool_engine_queue_depth", {"replica": "0"}, "gauge", 2.0),
            ("pool_engine_queue_depth", {"replica": "1"}, "gauge", 1.0),
            ("pool_completed_total", {}, "counter", clock.t * 10.0),
            ("pool_engine_hop_latency_seconds",
             {"replica": "0", "hop": "queue", "quantile": "0.99"},
             "gauge", 0.02),
            ("pool_engine_hop_latency_seconds",
             {"replica": "1", "hop": "queue", "quantile": "0.99"},
             "gauge", 0.05),
            ("pool_hop_conservation_frac", {}, "gauge", 1.0),
            ("pool_engine_hop_conservation_frac", {"replica": "0"},
             "gauge", 0.97),
            ("slo_burn_rate", {"class": "default", "window": "5m"},
             "gauge", 1.5),
        ]])
        for _ in range(6):
            _tick(st, clock)
        sig = st.signals()
        assert sig["t"] == st.doc()["last_t"]
        assert sig["queue_depth"] == 3.0          # engine tier sum
        assert sig["admitted_depth"] == 3.0       # pool rollup
        assert sig["hop_p99_s"] == {"queue": 0.05}  # worst replica
        assert sig["hop_conservation_frac"] == 0.97  # worst layer
        assert sig["burn_rate"] == {"default": {"5m": 1.5}}
        assert sig["completed_rate"] == pytest.approx(10.0)

    def test_signals_absent_is_none_not_zero(self):
        clock = FakeClock()
        st = _store(None, clock, sources=[lambda: [
            ("unrelated", {}, "gauge", 1.0)]])
        _tick(st, clock)
        sig = st.signals()
        assert sig["queue_depth"] is None
        assert sig["completed_rate"] is None
        assert st.signals(now=None) is not None
        assert _store().signals() == {"t": None}  # never sampled


class TestPersistenceAndReplay:
    def _seed(self, tmp_path, shard_records=4):
        clock = FakeClock()
        reg = Registry()
        c = reg.counter("done_total")
        g = reg.gauge("depth", labels={"replica": "0"})
        path = str(tmp_path / "events_history.jsonl")
        st = HistoryStore(reg, cadence_s=0.25, clock=clock,
                          persist_path=path, shard_records=shard_records,
                          run_id="t-run")
        for i in range(10):
            c.inc(i + 1)
            g.set(float(i % 3))
            dt = 2.0 if i == 6 else 0.25   # one blackout mid-stream
            clock.advance(dt)
            st.sample_now()
        st.close()
        return path, st

    def test_rotation_shards_and_headers(self, tmp_path):
        path, _ = self._seed(tmp_path)
        shards = discover_history_shards(path)
        assert len(shards) == 3            # 10 ticks / 4 per shard
        assert shards[1].endswith(".p1") and shards[2].endswith(".p2")
        for i, p in enumerate(shards):
            recs = read_events(p)
            assert recs[0]["event"] == "history_start"
            assert recs[0]["shard"] == i
            assert recs[0]["run_id"] == "t-run"
            # every shard is self-describing: series re-declared
            declared = {r["key"] for r in recs
                        if r["event"] == "history_series"}
            sampled = set()
            for r in recs:
                if r["event"] == "history_sample":
                    sampled |= set(r["v"])
            assert sampled <= declared

    def test_replay_is_bit_identical_on_every_derived_signal(
            self, tmp_path):
        path, live = self._seed(tmp_path)
        rep = HistoryStore.replay(path)

        def feed(st):
            return {
                "keys": st.keys(),
                "latest": st.latest(series_key("done_total")),
                "rate": st.rate(series_key("done_total"), 10.0),
                "trend": st.trend(series_key("done_total"), 10.0),
                "quantiles": st.window_quantiles(
                    series_key("depth", {"replica": "0"}), 10.0),
                "integral": st.integrate_rate(series_key("done_total")),
                "signals": st.signals(),
                "gaps": st.doc()["gaps"],
                "samples": st.doc()["samples"],
                "raw": st.query(series_key("done_total"))["points"],
                "agg": st.query(series_key("done_total"),
                                step=5.0)["points"],
            }

        assert feed(live) == feed(rep)     # ==, no tolerance
        assert rep.run_id == "t-run"

    def test_replay_missing_stream_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            HistoryStore.replay(str(tmp_path / "absent.jsonl"))

    def test_history_path_convention(self):
        assert history_path_for("/x/events.jsonl") \
            == "/x/events_history.jsonl"
        assert discover_history_shards("/nonexistent/h.jsonl") == []

    def test_shard_discovery_sorts_numerically(self, tmp_path):
        base = str(tmp_path / "h.jsonl")
        for p in [base] + [f"{base}.p{i}" for i in (1, 2, 9, 10, 11)]:
            with open(p, "w") as f:
                f.write("{}\n")
        shards = discover_history_shards(base)
        assert [os.path.basename(s) for s in shards[-3:]] \
            == ["h.jsonl.p9", "h.jsonl.p10", "h.jsonl.p11"]


class TestSampleUnderScrapeHammer:
    def test_eight_reader_threads_against_the_sampler(self, tmp_path):
        """8 reader threads hammering query/signals/rate/doc against a
        sampler folding as fast as it can, then exact conservation at
        quiescence: the last sample must equal the counter — a torn
        fold or a lost tick cannot hide."""
        reg = Registry()
        c = reg.counter("done_total")
        g = reg.gauge("depth")
        st = HistoryStore(reg, cadence_s=0.001,
                          persist_path=str(tmp_path / "h.jsonl"),
                          shard_records=200)
        stop = threading.Event()
        errors = []
        reads = [0]

        def writer():
            i = 0
            while not stop.is_set():
                c.inc()
                g.set(float(i % 7))
                i += 1

        def reader():
            n = 0
            key = series_key("done_total")
            while not stop.is_set():
                try:
                    st.doc()
                    st.signals()
                    st.rate(key, 1.0)
                    st.window_quantiles("depth", 1.0)
                    try:
                        st.query(key, limit=50)
                    except KeyError:
                        pass           # before the first tick landed
                    n += 1
                except Exception as e:  # noqa: BLE001 — the failure
                    errors.append(repr(e))   # under test
                    return
            reads[0] += n

        threads = [threading.Thread(target=writer)]
        threads += [threading.Thread(target=reader) for _ in range(8)]
        st.start()
        for t in threads:
            t.start()
        import time as _time
        _time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        st.stop()
        assert not errors, errors[:3]
        assert reads[0] > 0
        # quiescence: one forced tick, then three views agree EXACTLY
        t_fin = st.sample_now()
        key = series_key("done_total")
        assert st.latest(key) == (t_fin, c.value)
        assert st.doc()["sample_errors"] == 0
        st.close()
        # and the persisted stream replays to the same final value
        rep = HistoryStore.replay(str(tmp_path / "h.jsonl"))
        assert rep.latest(key) == (t_fin, c.value)


class TestCapacityModel:
    POINTS = [(10.0, 20.0), (20.0, 22.0), (40.0, 30.0),
              (80.0, 45.0), (100.0, 140.0)]

    def test_knee_from_base_latency_factor(self):
        m = CapacityModel.fit_from_points(self.POINTS, replicas=2)
        assert m.base_ms == 20.0
        assert m.objective_ms == 40.0      # 2.0 x base
        assert m.knee_qps == 40.0          # last point inside 40 ms
        assert m.per_replica_qps() == 20.0
        assert m.measured_max_qps == 100.0

    def test_replicas_needed_with_headroom_and_flags(self):
        m = CapacityModel.fit_from_points(self.POINTS, replicas=2)
        need = m.replicas_needed(68.0, headroom=0.85)
        assert need["replicas"] == 4       # ceil(68 / (20*0.85))
        assert need["objective_unmet"] is False
        assert need["extrapolated"] is False
        far = m.replicas_needed(500.0)
        assert far["extrapolated"] is True
        # explicit objective re-evaluates the knee without refitting
        tight = m.replicas_needed(30.0, objective_ms=21.0)
        assert tight["knee_qps"] == 10.0

    def test_objective_unmet_is_flagged_not_faked(self):
        m = CapacityModel.fit_from_points(
            [(10.0, 50.0), (20.0, 80.0)], objective_ms=10.0)
        need = m.replicas_needed(15.0)
        assert need["replicas"] is None
        assert need["objective_unmet"] is True

    def test_no_measurements_answers_none(self):
        m = CapacityModel.fit_from_points([])
        assert m.knee_qps is None
        assert m.replicas_needed(10.0)["replicas"] is None

    def test_occupancy_headroom(self):
        m = CapacityModel(
            [{"qps": 10.0, "mean_ms": 5.0, "occupancy": 6.0},
             {"qps": 30.0, "mean_ms": 50.0, "occupancy": 8.0}],
            objective_ms=10.0, max_batch=8)
        assert m.knee_occupancy == 6.0
        assert m.occupancy_headroom() == pytest.approx(0.25)

    def test_fit_from_history_store_with_prefix(self):
        """The exact-counter fit path: a synthetic pool_* load ramp in a
        store (two 1 s plateaus at 10 then 40 qps with known latency
        sums) fits windows whose qps/mean are the counter deltas."""
        clock = FakeClock()
        state = {"done": 0.0, "lat": 0.0, "qps": 10.0, "ms": 10.0}

        def src():
            return [
                ("pool_completed_total", {}, "counter", state["done"]),
                ("pool_latency_seconds_sum", {}, "counter",
                 state["lat"]),
                ("pool_latency_seconds_count", {}, "counter",
                 state["done"]),
                ("pool_batch_occupancy_mean", {}, "gauge", 4.0),
            ]

        st = _store(None, clock, sources=[src])
        for i in range(17):
            if i == 8:
                state["qps"], state["ms"] = 40.0, 35.0
            state["done"] += state["qps"] * 0.25
            state["lat"] += state["qps"] * 0.25 * state["ms"] / 1e3
            _tick(st, clock)
        m = CapacityModel.fit(st, window_s=1.0, prefix="pool")
        assert m.meta["prefix"] == "pool"
        assert len(m.points) >= 3
        qps = [round(p["qps"]) for p in m.points]
        assert 10 in qps and 40 in qps
        # pure plateau windows carry the exact counter-delta latency;
        # the one window straddling the transition is a blend and is
        # deliberately not pinned
        for p in m.points:
            if round(p["qps"]) == 10:
                assert p["mean_ms"] == pytest.approx(10.0, abs=1e-6)
            elif round(p["qps"]) == 40:
                assert p["mean_ms"] == pytest.approx(35.0, abs=1e-6)
            assert p["occupancy"] == pytest.approx(4.0)
        # serve-prefixed fit over the same store sees nothing
        assert CapacityModel.fit(st, window_s=1.0).points == []

    def test_register_into_exports_capacity_gauges(self):
        reg = Registry()
        m = CapacityModel.fit_from_points(self.POINTS, replicas=2)
        m.register_into(reg)
        snap = reg.snapshot()
        assert snap["capacity_knee_qps"] == 40.0
        assert snap["capacity_replicas"] == 2.0


class TestHistoryRoutes:
    def _served(self):
        clock = FakeClock()
        reg = Registry()
        c = reg.counter("done_total")
        st = _store(reg, clock)
        for _ in range(6):
            c.inc(2)
            _tick(st, clock)
        return reg, st

    def test_history_and_query_roundtrip_with_head_parity(self):
        reg, st = self._served()
        with MetricsServer(reg, port=0, history=st) as srv:
            with urllib.request.urlopen(srv.url + "/history",
                                        timeout=10) as r:
                doc = json.loads(r.read().decode())
                glen = int(r.headers["Content-Length"])
            assert doc["samples"] == 6
            assert series_key("done_total") in doc["keys"]
            req = urllib.request.Request(srv.url + "/history",
                                         method="HEAD")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert int(r.headers["Content-Length"]) == glen
                assert r.read() == b""
            q_url = (srv.url + "/query?series="
                     + urllib.parse.quote(series_key("done_total"))
                     + "&limit=3")
            with urllib.request.urlopen(q_url, timeout=10) as r:
                q = json.loads(r.read().decode())
            assert q["truncated"] is True and len(q["points"]) == 3
            with urllib.request.urlopen(q_url + "&step=5",
                                        timeout=10) as r:
                agg = json.loads(r.read().decode())
            assert agg["step"] == 5.0
            assert agg["points"][-1]["count"] >= 1

    def test_query_error_codes(self):
        reg, st = self._served()

        def code(path):
            try:
                urllib.request.urlopen(srv.url + path, timeout=10)
                return 200
            except urllib.error.HTTPError as e:
                return e.code

        with MetricsServer(reg, port=0, history=st) as srv:
            assert code("/query") == 400
            assert code("/query?series=nope") == 404
            assert code("/query?series=done_total&since=zzz") == 400
            assert code("/query?series=done_total") == 200

    def test_unwired_history_is_404_and_404_body_lists_routes(self):
        reg = Registry()
        with MetricsServer(reg, port=0) as srv:
            for path in ("/history", "/query?series=x"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(srv.url + path, timeout=10)
                assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/nope", timeout=10)
            body = ei.value.read().decode()
            for route in ("/metrics", "/history", "/query"):
                assert route in body

    def test_routes_table_matches_module_doc(self):
        from improved_body_parts_tpu.obs import ROUTES
        from improved_body_parts_tpu.obs import http as obs_http

        for path, _ in ROUTES:
            assert path in obs_http.__doc__


class TestReportVerifier:
    def _seed(self, tmp_path):
        clock = FakeClock()
        reg = Registry()
        c = reg.counter("done_total")
        path = str(tmp_path / "h.jsonl")
        st = HistoryStore(reg, cadence_s=0.25, clock=clock,
                          persist_path=path, shard_records=4,
                          run_id="vr")
        for i in range(9):
            c.inc()
            clock.advance(2.0 if i == 4 else 0.25)
            st.sample_now()
        st.close()
        return path

    def test_healthy_stream_verifies_ok(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from history_report import verify_history
        finally:
            sys.path.pop(0)
        path = self._seed(tmp_path)
        ok, problems, stats = verify_history(path)
        assert ok, problems
        assert stats["ticks"] == 9 and stats["shards"] == 3
        assert stats["gaps_persisted"] == stats["gaps_redetected"] == 1

    def test_broken_streams_cannot_pass_for_healthy(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from history_report import verify_history
        finally:
            sys.path.pop(0)
        path = self._seed(tmp_path)
        # 1: a dropped middle shard (numbering hole → position mismatch)
        os.rename(path + ".p1", path + ".p1.bak")
        ok, problems, _ = verify_history(path)
        assert not ok and any("shard" in p for p in problems)
        os.rename(path + ".p1.bak", path + ".p1")
        # 2: an undeclared series smuggled into a sample record
        with open(path + ".p2", "a") as f:
            t = read_events(path + ".p2")[-1]["t"] + 0.25
            f.write(strict_dumps({"event": "history_sample", "t": t,
                                  "v": {"ghost": 1.0}}) + "\n")
        ok, problems, _ = verify_history(path)
        assert not ok and any("undeclared" in p for p in problems)

    def test_report_cli_strict_renders_and_gates(self, tmp_path):
        import subprocess
        import sys
        path = self._seed(tmp_path)
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "history_report.py"), path,
             "--series", "done_total", "--strict"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr[-500:]
        assert "verifier: OK" in r.stdout
        assert "done_total" in r.stdout


class TestHistoryMetricNameLint:
    """The history/capacity families ride the same Prometheus naming
    rules the ISSUE 7 walk enforces — linted here over a registry that
    carries both collectors plus the store's own sampled meta-signals."""

    NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

    def test_history_and_capacity_names_are_prometheus_legal(self):
        clock = FakeClock()
        reg = Registry()
        reg.counter("done_total").inc()
        st = _store(reg, clock)
        st.register_into(reg)
        m = CapacityModel.fit_from_points(
            [(10.0, 20.0), (40.0, 90.0)], replicas=2)
        m.register_into(reg)
        clock.advance(0.25)
        st.sample_now()
        names = set()
        for name, labels, kind, value, help in reg._flat():
            names.add(name)
            assert self.NAME_RE.match(name), name
            for k in labels:
                assert self.LABEL_RE.match(str(k)), (name, k)
            if kind == "counter":
                assert name.endswith(("_total", "_sum", "_count")), name
        assert {"history_samples_total", "history_gaps_total",
                "history_series", "history_series_dropped_total",
                "history_sample_errors_total",
                "history_persist_records_total",
                "history_persist_shards"} <= names
        assert {"capacity_windows", "capacity_replicas",
                "capacity_base_latency_ms", "capacity_objective_ms",
                "capacity_knee_qps", "capacity_per_replica_qps",
                "capacity_measured_max_qps"} <= names
        # self-describing: the store sampled its own meta-signals
        assert "history_samples_total" in st.keys()


class TestGraftlintScope:
    def test_jgl002_scope_covers_history_module(self):
        """ISSUE 19 satellite: the history sampler runs while serving is
        live — locked into the JGL002 hot-path sweep on its actual
        path, so a move out of obs/ can't silently drop it."""
        from improved_body_parts_tpu.analysis.rules.host_sync import (
            HiddenHostSync,
        )

        assert "improved_body_parts_tpu/obs/history.py" \
            in HiddenHostSync.SCOPE
