"""Serving engine tests (``improved_body_parts_tpu.serve``).

A constant-maps stub predictor (the ``test_predictor`` pattern) isolates
the batcher's own machinery — shape-bucket coalescing, deadline flush,
admission/load-shedding, warmup precompile, result routing — from
network weights; a planted person makes results decodable and
per-request-distinguishable (different input sizes decode to different
coordinate scales, so a cross-request mixup cannot go unnoticed).
"""
import dataclasses
import threading
import time

import numpy as np
import pytest

from improved_body_parts_tpu.config import (
    InferenceModelParams,
    default_inference_params,
    get_config,
)

CFG = get_config("canonical")
SK = CFG.skeleton
SIZE_A = (256, 256)          # lane bucket (256, 256)
SIZE_B = (192, 256)          # scaled to 256x341 -> lane bucket (256, 384)


class StubModel:
    """Ignores the input image; returns fixed stride-4 maps for whatever
    spatial size it is given (all forward lanes see the same maps)."""

    def __init__(self, maps):
        self.maps = maps

    def apply(self, variables, imgs, train=False):
        import jax.numpy as jnp

        n, h, w, _ = imgs.shape
        maps = jnp.asarray(self.maps[:h // SK.stride, :w // SK.stride])
        return [[jnp.broadcast_to(maps, (n, *maps.shape))]]


def _person_maps():
    """Stride-grid GT maps with one planted symmetric person on a 256px
    canvas (the test_predictor person), tie-broken with tiny noise."""
    from improved_body_parts_tpu.data.heatmapper import Heatmapper

    h = w = 256
    joints = np.zeros((1, SK.num_parts, 3), np.float32)
    joints[:, :, 2] = 2
    cx = (w - 1) / 2

    def put(name, dx, y):
        joints[0, SK.parts_dict[name]] = [cx + dx, y, 1]

    put("nose", 0, 40)
    put("neck", 0, 70)
    for lr, sgn in (("R", -1), ("L", 1)):
        put(lr + "sho", sgn * 30, 75)
        put(lr + "elb", sgn * 42, 110)
        put(lr + "wri", sgn * 46, 145)
        put(lr + "hip", sgn * 18, 150)
        put(lr + "kne", sgn * 20, 195)
        put(lr + "ank", sgn * 21, 240)
        put(lr + "eye", sgn * 8, 34)
        put(lr + "ear", sgn * 14, 38)
    small = dataclasses.replace(SK, width=w, height=h)
    maps = Heatmapper(small).create_heatmaps(
        joints, np.ones(small.grid_shape, np.float32))
    rng = np.random.default_rng(1)
    return (maps + rng.uniform(0, 1e-6, maps.shape)).astype(np.float32)


def _make_pred(maps, **kw):
    from improved_body_parts_tpu.infer import Predictor

    params, _ = default_inference_params()
    model_params = InferenceModelParams(boxsize=256, max_downsample=64)
    return Predictor(StubModel(maps), {}, SK, params, model_params,
                     bucket=64, **kw)


@pytest.fixture(scope="module")
def person_maps():
    return _person_maps()


@pytest.fixture(scope="module")
def warm_pred(person_maps):
    """One predictor shared by the routing/flush tests (its jitted
    program cache persists across tests, so compiles are paid once)."""
    return _make_pred(person_maps)


def _reference(pred, img):
    from improved_body_parts_tpu.infer import decode_compact

    return decode_compact(pred.predict_compact(img), pred.params,
                          SK, use_native=False)


def _assert_same_people(got, want, tol=0.05):
    assert len(got) == len(want)
    for (gk, gs), (wk, ws) in zip(
            sorted(got, key=lambda r: -r[1]),
            sorted(want, key=lambda r: -r[1])):
        assert gs == pytest.approx(ws, abs=1e-3)
        for pg, pw in zip(gk, wk):
            assert (pg is None) == (pw is None)
            if pg is not None:
                assert pg[0] == pytest.approx(pw[0], abs=tol)
                assert pg[1] == pytest.approx(pw[1], abs=tol)


class GatedPredictor:
    """Delegates to a real predictor but holds every device dispatch at a
    gate — deterministic control of 'device busy' for shed tests."""

    def __init__(self, inner, gate):
        self._inner, self._gate = inner, gate

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def predict_compact_async(self, *a, **kw):
        self._gate.wait()
        return self._inner.predict_compact_async(*a, **kw)

    def predict_compact_batch_async(self, *a, **kw):
        self._gate.wait()
        return self._inner.predict_compact_batch_async(*a, **kw)

    # the batcher's default device-decode lane dispatches these instead
    def predict_decoded_async(self, *a, **kw):
        self._gate.wait()
        return self._inner.predict_decoded_async(*a, **kw)

    def predict_decoded_batch_async(self, *a, **kw):
        self._gate.wait()
        return self._inner.predict_decoded_batch_async(*a, **kw)


# --------------------------------------------------------------------- #
def test_pow2_batch_sizes():
    from improved_body_parts_tpu.serve import pow2_batch_sizes

    assert pow2_batch_sizes(1) == (1,)
    assert pow2_batch_sizes(6) == (1, 2, 4)
    assert pow2_batch_sizes(8) == (1, 2, 4, 8)
    with pytest.raises(ValueError):
        pow2_batch_sizes(0)


def test_percentile_meter():
    from improved_body_parts_tpu.utils import PercentileMeter

    m = PercentileMeter(capacity=1000)
    for v in range(1, 101):
        m.update(float(v))
    assert m.count == 100
    assert m.avg == pytest.approx(50.5)
    assert m.percentile(50) == pytest.approx(50.5)
    assert m.percentile(99) == pytest.approx(99.01)
    s = m.summary(scale=10.0)
    assert s["count"] == 100 and s["p95"] == pytest.approx(950.5)

    # bounded memory: the reservoir never exceeds its capacity
    small = PercentileMeter(capacity=8)
    for v in range(10000):
        small.update(float(v))
    assert len(small._samples) == 8 and small.count == 10000


def test_batcher_rejects_grid_params(warm_pred):
    from improved_body_parts_tpu.config import InferenceParams
    from improved_body_parts_tpu.serve import DynamicBatcher

    with pytest.raises(ValueError, match="single-scale"):
        DynamicBatcher(warm_pred,
                       InferenceParams(scale_search=(0.5, 1.0)))


def test_concurrent_submitters_get_their_own_results(warm_pred):
    """8 threads × mixed sizes: every future must resolve to ITS image's
    skeletons (sizes decode at different coordinate scales, so routing
    mixups are visible), across two shape buckets."""
    from improved_body_parts_tpu.serve import DynamicBatcher

    imgs = {s: np.zeros((*s, 3), np.uint8) for s in (SIZE_A, SIZE_B)}
    refs = {s: _reference(warm_pred, im) for s, im in imgs.items()}
    # the two sizes really decode at different scales (mixups detectable)
    nose_a = max(refs[SIZE_A], key=lambda r: r[1])[0][0]
    nose_b = max(refs[SIZE_B], key=lambda r: r[1])[0][0]
    assert abs(nose_a[0] - nose_b[0]) > 5

    with DynamicBatcher(warm_pred, max_batch=2, max_wait_ms=30,
                        max_queue=64, use_native=False) as server:
        server.warmup([SIZE_A, SIZE_B], batch_sizes=(1, 2))
        results = {}

        def client(tid):
            out = []
            for i in range(3):
                size = (SIZE_A, SIZE_B)[(tid + i) % 2]
                out.append((size, server.submit(imgs[size])))
            results[tid] = [(s, f.result(timeout=60)) for s, f in out]

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = server.metrics.snapshot()

    for tid, pairs in results.items():
        for size, got in pairs:
            _assert_same_people(got, refs[size])
    assert snap["submitted"] == snap["completed"] == 24
    assert snap["failed"] == snap["rejected"] == 0


def test_deadline_flush_single_straggler(warm_pred):
    """One lone request must not wait for a full batch: with occupancy 1
    and max_batch 8 the deadline (or idle-device) flush serves it."""
    from improved_body_parts_tpu.serve import DynamicBatcher

    img = np.zeros((*SIZE_A, 3), np.uint8)
    ref = _reference(warm_pred, img)
    # eager_idle_flush off: completion proves the DEADLINE path flushes
    with DynamicBatcher(warm_pred, max_batch=8, max_wait_ms=50,
                        use_native=False,
                        eager_idle_flush=False) as server:
        server.warmup([SIZE_A], batch_sizes=(1,))
        t0 = time.perf_counter()
        got = server.submit(img).result(timeout=60)
        waited = time.perf_counter() - t0
        snap = server.metrics.snapshot()
    _assert_same_people(got, ref)
    # flushed by the 50 ms deadline, not by an 8-deep batch that never
    # arrives (generous bound: warm programs decode in well under 10 s)
    assert waited < 10.0
    assert snap["occupancy_histogram"] == {"1": 1}


def test_occupancy_accounting_two_buckets(warm_pred):
    """4 size-A + 3 size-B requests, max_batch=4, deterministic flushes:
    bucket A flushes full (occupancy 4), bucket B on the deadline
    (occupancy 3) — the histogram and mean must say exactly that."""
    from improved_body_parts_tpu.serve import DynamicBatcher

    imgs = {s: np.zeros((*s, 3), np.uint8) for s in (SIZE_A, SIZE_B)}
    with DynamicBatcher(warm_pred, max_batch=4, max_wait_ms=300,
                        use_native=False,
                        eager_idle_flush=False) as server:
        server.warmup([SIZE_A, SIZE_B], batch_sizes=(1, 2, 4))
        futs = [server.submit(imgs[SIZE_A]) for _ in range(4)]
        futs += [server.submit(imgs[SIZE_B]) for _ in range(3)]
        for f in futs:
            f.result(timeout=120)
        snap = server.metrics.snapshot()
    assert snap["occupancy_histogram"] == {"3": 1, "4": 1}
    assert snap["mean_batch_occupancy"] == pytest.approx(3.5)
    assert snap["completed"] == 7


def test_load_shed_fails_fast_and_keeps_serving(warm_pred):
    """With the admission queue full, submit() must raise
    ServerOverloaded immediately (no blocking, nothing queued) while
    everything already admitted still completes."""
    from improved_body_parts_tpu.serve import (
        DynamicBatcher, ServerOverloaded)

    img = np.zeros((*SIZE_A, 3), np.uint8)
    ref = _reference(warm_pred, img)
    gate = threading.Event()
    gated = GatedPredictor(warm_pred, gate)
    server = DynamicBatcher(gated, max_batch=1, max_wait_ms=5,
                            max_queue=2, use_native=False)
    with server:
        f1 = server.submit(img)
        f2 = server.submit(img)
        # give the dispatcher a beat to park on the gate
        time.sleep(0.05)
        t0 = time.perf_counter()
        with pytest.raises(ServerOverloaded):
            server.submit(img)
        assert time.perf_counter() - t0 < 0.5  # fail-FAST, no blocking
        assert server.metrics.rejected == 1
        gate.set()  # device 'recovers': in-flight work drains
        _assert_same_people(f1.result(timeout=60), ref)
        _assert_same_people(f2.result(timeout=60), ref)
        # the shed was transient: the server accepts and serves again
        _assert_same_people(server.submit(img).result(timeout=60), ref)
    snap = server.metrics.snapshot()
    assert snap["completed"] == 3 and snap["rejected"] == 1
    assert snap["queue_depth"] == 0


def test_compact_overflow_falls_back_to_full_maps(person_maps):
    """A request whose peak count overflows the compact top-K capacity
    must still yield correct skeletons (transparent full-map fallback,
    the pipeline's documented behavior)."""
    from improved_body_parts_tpu.infer import decode
    from improved_body_parts_tpu.serve import DynamicBatcher

    pred = _make_pred(person_maps, compact_topk=1)
    img = np.zeros((*SIZE_A, 3), np.uint8)
    res = pred.predict_compact(img)
    assert bool((res.peaks.count > res.peaks.valid.shape[1]).any()), \
        "fixture no longer overflows topk=1; tighten it"

    heat, paf, mask, scale = pred.predict_fast(img)
    want = decode(heat, paf, pred.params, SK, peak_mask=mask,
                  coord_scale=scale, use_native=False)

    with DynamicBatcher(pred, max_batch=2, max_wait_ms=20,
                        use_native=False) as server:
        server.warmup([SIZE_A], batch_sizes=(1, 2))
        got = server.submit(img).result(timeout=120)
        snap = server.metrics.snapshot()
    _assert_same_people(got, want)
    # the overflow was served by the demoted host decode pool — and the
    # split metric makes that fallback observable
    assert snap["decode_host_fallback"] == 1
    assert snap["decode_fused"] == 0


def test_device_decode_is_the_default_lane(warm_pred):
    """The default lane runs the FUSED device program end to end: every
    request finishes inline off the device payload (decode_fused) with
    zero host-pool fallbacks, and the payload matches the host
    decoder's people exactly."""
    from improved_body_parts_tpu.serve import DynamicBatcher

    img = np.zeros((*SIZE_A, 3), np.uint8)
    ref = _reference(warm_pred, img)
    with DynamicBatcher(warm_pred, max_batch=2, max_wait_ms=20,
                        use_native=False) as server:
        assert server.device_decode
        server.warmup([SIZE_A], batch_sizes=(1, 2))
        futs = [server.submit(img) for _ in range(4)]
        for f in futs:
            _assert_same_people(f.result(timeout=120), ref)
        snap = server.metrics.snapshot()
    assert snap["decode_fused"] == 4
    assert snap["decode_host_fallback"] == 0
    assert snap["completed"] == 4


def test_hop_waterfall_conserves_e2e_on_warm_batcher(warm_pred):
    """ISSUE 15 satellite: on a REAL warm batcher (jitted fused-decode
    programs, warmed buckets) the five-hop waterfall
    (queue/batch_formation/device/decode/deliver) must account for
    >=95% of the measured end-to-end latency — the conservation
    discipline that makes 'which hop ate the budget' a trustworthy
    question.  The partition is exact by construction (shared boundary
    stamps); this pins that the plumbing actually stamps every stage on
    both the batch and singleton-flush paths."""
    from improved_body_parts_tpu.serve import DynamicBatcher
    from improved_body_parts_tpu.serve.metrics import HOPS

    img = np.zeros((*SIZE_A, 3), np.uint8)
    with DynamicBatcher(warm_pred, max_batch=2, max_wait_ms=20,
                        use_native=False) as server:
        server.warmup([SIZE_A], batch_sizes=(1, 2))
        futs = [server.submit(img) for _ in range(6)]
        for f in futs:
            f.result(timeout=120)
        snap = server.metrics.snapshot()
    assert snap["completed"] == 6
    for hop in HOPS:
        assert snap["hops_ms"][hop]["count"] == 6
    assert snap["hop_conservation_frac"] >= 0.95
    # sums, not estimates: hop sums vs the exact e2e reservoir sum
    hop_total = sum(snap["hops_ms"][h]["sum"] for h in HOPS)
    e2e_total = (snap["latency_ms"]["mean"]
                 * snap["latency_ms"]["count"])
    assert hop_total == pytest.approx(e2e_total, rel=0.05)


def test_host_pool_lane_still_serves(warm_pred):
    """device_decode=False keeps the pre-fusion decode-pool lane alive
    (the A/B + parity arm): same people, everything counted as
    host-pool decode."""
    from improved_body_parts_tpu.serve import DynamicBatcher

    img = np.zeros((*SIZE_A, 3), np.uint8)
    ref = _reference(warm_pred, img)
    with DynamicBatcher(warm_pred, max_batch=2, max_wait_ms=20,
                        use_native=False,
                        device_decode=False) as server:
        server.warmup([SIZE_A], batch_sizes=(1, 2))
        _assert_same_people(server.submit(img).result(timeout=120), ref)
        snap = server.metrics.snapshot()
    assert snap["decode_fused"] == 0
    assert snap["decode_host_fallback"] == 1


def test_warmup_precompiles_every_bucket_program(person_maps):
    """After warmup, serving traffic over every configured bucket (full
    batches, pow2 splits, singleton stragglers) must hit only cached
    programs — the no-compile-stall-on-first-request guarantee, asserted
    on the predictor's program-cache keys."""
    from improved_body_parts_tpu.serve import DynamicBatcher

    pred = _make_pred(person_maps)
    imgs = {s: np.zeros((*s, 3), np.uint8) for s in (SIZE_A, SIZE_B)}
    with DynamicBatcher(pred, max_batch=4, max_wait_ms=30,
                        use_native=False) as server:
        info = server.warmup([SIZE_A, SIZE_B])
        assert info["bucket_shapes"] == [(256, 256), (256, 384)]
        assert info["batch_sizes"] == (1, 2, 4)
        assert info["newly_compiled"] > 0
        keys = set(pred._fns)

        # a second warmup is a no-op: everything is already compiled
        assert server.warmup([SIZE_A, SIZE_B])["newly_compiled"] == 0

        futs = [server.submit(imgs[(SIZE_A, SIZE_B)[i % 2]])
                for i in range(11)]
        for f in futs:
            f.result(timeout=120)
    # jit-cache hit count: serving added NO programs beyond the warmup
    # set, so no request paid a compile
    assert set(pred._fns) == keys


@pytest.mark.slow
def test_serve_bench_cli(tmp_path):
    """tools/serve_bench.py end-to-end on the tiny config: writes
    SERVE_BENCH.json with throughput + tail latency + occupancy."""
    import json
    import os
    import subprocess
    import sys

    out = tmp_path / "SERVE_BENCH.json"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--config", "tiny", "--sizes", "128", "--boxsize", "128",
         "--requests", "2", "--clients", "2", "--baseline-clients", "2",
         "--max-batch", "2", "--rounds", "1", "--planted", "1",
         "--out", str(out)],
        check=True, timeout=1500, env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    r = json.loads(out.read_text())
    assert r["platform"]
    serve = r["serve_at_peak_load"]
    for k in ("p50", "p95", "p99"):
        assert serve["latency_ms"][k] > 0
    assert serve["imgs_per_sec"] > 0
    assert serve["mean_batch_occupancy"] >= 1
    assert r["sequential"]["imgs_per_sec"] > 0
    assert isinstance(r["batched_beats_sequential"], bool)
    # ISSUE 15 satellite: the per-hop decomposition block rides the
    # artifact next to the e2e numbers
    for k in ("queue", "batch_formation", "device", "decode",
              "deliver"):
        assert serve["hops_ms"][k]["count"] > 0
    assert serve["hop_conservation_frac"] >= 0.95


@pytest.mark.slow
def test_serve_bench_proc_only_cli(tmp_path):
    """tools/serve_bench.py --proc-only: the process-pool A/B artifact
    carries the ISSUE 18 fleet-plane block — per-worker hop quantiles
    read back over the shm telemetry wire (worker-VIEW, measured in the
    process that paid them) and the cross-boundary conservation ledger
    (router-view submitted vs Σ worker-view served + in-flight)."""
    import json
    import os
    import subprocess
    import sys

    out = tmp_path / "PROC_BENCH.json"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--proc-only", "--proc-rounds", "2", "--requests", "6",
         "--telemetry-sink", "none", "--out", str(out)],
        check=True, timeout=1500, env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    r = json.loads(out.read_text())
    ab = r["proc_ab"]
    quant = ab["process_worker_hop_quantiles_ms"]
    assert len(quant) == ab["workers"]
    for w in quant:
        assert w["published"], w
        for hop in ("device", "decode"):
            h = w["hops_ms"][hop]
            assert h["count"] > 0
            assert h["p50"] > 0 and h["p95"] >= h["p50"]
            assert h["p99"] >= h["p95"]
    cons = ab["cross_boundary_conservation"]
    assert cons["router_submitted"] > 0
    # clean run: the ledger balances (each worker's final count beat
    # lands just AFTER the parent's future resolves, so the readback
    # may trail by at most one request per worker — the documented
    # chaos-tolerant gate, not an equality assert)
    assert cons["frac"] >= 0.95


def test_metrics_endpoint_serves_batcher_under_load(warm_pred):
    """Acceptance (ISSUE 3): a live /metrics endpoint serves valid
    Prometheus text exposition for a DynamicBatcher under concurrent
    load, through the shared obs.Registry path."""
    import re
    import urllib.request

    from improved_body_parts_tpu.obs import MetricsServer, Registry
    from improved_body_parts_tpu.serve import DynamicBatcher

    reg = Registry()
    img = np.zeros((*SIZE_A, 3), np.uint8)
    with DynamicBatcher(warm_pred, max_batch=2, max_wait_ms=30,
                        max_queue=64, use_native=False,
                        registry=reg) as server, \
            MetricsServer(reg, port=0) as srv:
        server.warmup([SIZE_A], batch_sizes=(1, 2))

        def client():
            for _ in range(3):
                server.submit(img).result(timeout=60)

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        # scrape WHILE load is in flight — the endpoint must hold up
        mid = urllib.request.urlopen(srv.url + "/metrics",
                                     timeout=10).read().decode()
        assert "serve_submitted_total" in mid
        for t in threads:
            t.join()
        body = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=10).read().decode()

    line_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+-]+$")
    for line in body.strip().splitlines():
        if not line.startswith("#"):
            assert line_re.match(line), f"malformed exposition: {line!r}"
    assert "serve_submitted_total 12.0" in body
    assert "serve_completed_total 12.0" in body
    assert 'serve_latency_seconds{quantile="0.99"}' in body
    assert "serve_imgs_per_sec" in body


def test_graceful_drain_flushes_queued_and_rejects_new(warm_pred):
    """stop(drain_timeout_s=...) closes admission FIRST (ServerOverloaded
    — the status a load balancer already retries on during rollout),
    then flushes everything admitted: no queued request is stranded."""
    from improved_body_parts_tpu.serve import (
        DynamicBatcher, ServerOverloaded)

    img = np.zeros((*SIZE_A, 3), np.uint8)
    ref = _reference(warm_pred, img)
    gate = threading.Event()
    gated = GatedPredictor(warm_pred, gate)
    server = DynamicBatcher(gated, max_batch=1, max_wait_ms=5,
                            max_queue=8, use_native=False).start()
    f1 = server.submit(img)
    f2 = server.submit(img)
    time.sleep(0.05)  # let the dispatcher park on the gate
    stopper = threading.Thread(target=lambda: server.stop(
        drain_timeout_s=120.0))
    stopper.start()
    deadline = time.time() + 10
    while not server.draining and time.time() < deadline:
        time.sleep(0.005)
    assert server.draining
    with pytest.raises(ServerOverloaded, match="draining"):
        server.submit(img)
    gate.set()  # device 'recovers': the admitted work drains out
    stopper.join(timeout=120)
    assert not stopper.is_alive()
    # both admitted futures completed with real results — not stranded
    _assert_same_people(f1.result(timeout=0), ref)
    _assert_same_people(f2.result(timeout=0), ref)


def test_drain_deadline_fails_stranded_futures(warm_pred):
    """A wedged device must not hang shutdown forever: past
    drain_timeout_s every still-in-flight future fails with an explicit
    error — every future submit() ever returned always completes."""
    from improved_body_parts_tpu.serve import DynamicBatcher

    img = np.zeros((*SIZE_A, 3), np.uint8)
    gate = threading.Event()  # never set until after: device is wedged
    gated = GatedPredictor(warm_pred, gate)
    server = DynamicBatcher(gated, max_batch=1, max_wait_ms=5,
                            max_queue=8, use_native=False).start()
    f1 = server.submit(img)
    time.sleep(0.05)
    t0 = time.perf_counter()
    server.stop(drain_timeout_s=1.5)
    assert time.perf_counter() - t0 < 30.0  # bounded, not wait-forever
    with pytest.raises(RuntimeError, match="drain deadline"):
        f1.result(timeout=0)
    gate.set()  # release the parked daemon thread (exactly-once _finish
    # makes its late completion a harmless no-op)


def test_stop_without_deadline_still_drains_everything(warm_pred):
    """The historical contract unchanged: a deadline-less stop() waits
    for every admitted request."""
    from improved_body_parts_tpu.serve import DynamicBatcher

    img = np.zeros((*SIZE_A, 3), np.uint8)
    ref = _reference(warm_pred, img)
    server = DynamicBatcher(warm_pred, max_batch=2, max_wait_ms=20,
                            use_native=False).start()
    server.warmup([SIZE_A], batch_sizes=(1, 2))
    futs = [server.submit(img) for _ in range(4)]
    server.stop()
    for f in futs:
        _assert_same_people(f.result(timeout=0), ref)
    snap = server.metrics.snapshot()
    assert snap["completed"] == 4 and snap["failed"] == 0
