"""Process-isolated serving tests: ``serve.worker`` + ``serve.router``
(ISSUE 16).

Three tiers, none of which pays an XLA compile:

- **Wire units** — encode/decode round-trips of the fixed-shape person
  table, bit-exactly, without spawning anything.
- **Engine contract on one worker process** — submit/health/drain/
  deadline/error delivery through the shared-memory transport, plus
  the respawn discipline (backoff counters, crash budget) driven by a
  real SIGKILL.
- **Fleet semantics** — a ``ProcessRouter``'s pool carries the PR 11
  fence/failover/breaker logic across the process boundary: bit
  identity against an in-process thread arm on the SAME fake
  predictor, kill-mid-flight failover with zero lost futures, drain
  discipline (every future resolves on ``stop()``), and reqtrace
  causal completeness over a process-pool run.
"""
import os
import signal
import time
from concurrent.futures import Future

import numpy as np
import pytest

from improved_body_parts_tpu.infer.decode import EscalationSignals
from improved_body_parts_tpu.serve import (
    DeadlineExceeded,
    EnginePool,
    ProcessRouter,
    ProcessWorkerEngine,
    ServeMetrics,
    ServerOverloaded,
)
from improved_body_parts_tpu.serve.worker import (
    constant_predictor,
    decode_people,
    encode_people,
    wire_format,
)

SPEC = "improved_body_parts_tpu.serve.worker:constant_predictor"
NUM_PARTS = 6

ENGINE_KW = dict(max_image_hw=(64, 64), num_parts=NUM_PARTS,
                 max_people=8, slots=8)


def _img(value: int, hw=(32, 32)) -> np.ndarray:
    return np.full((*hw, 3), value, np.uint8)


def _conserved(m: ServeMetrics) -> bool:
    return m.submitted == m.completed + m.failed + m.depth


# --------------------------------------------------------------------- #
# wire units                                                             #
# --------------------------------------------------------------------- #
class TestWire:
    def _views(self, max_people=8, num_parts=NUM_PARTS):
        _, shapes, dtypes = wire_format((64, 64), num_parts, max_people)
        kps = np.zeros(shapes[2], np.float64)
        scores = np.zeros(shapes[3], np.float64)
        sig = np.zeros(shapes[4], np.float64)
        meta_out = np.zeros(shapes[5], np.float64)
        return kps, scores, sig, meta_out

    def test_roundtrip_bit_identity(self):
        people = [
            ([(1.5, 2.25), None, (-3.0, 4.125), (0.1, 0.2), None,
              (7.0, 8.0)], 3.375),
            ([None, (10.5, 11.0), (12.0, 13.5), None, (1e-9, 2e9),
              (0.0625, 0.03125)], -1.5),
        ]
        signals = EscalationSignals(2, False, True, False,
                                    float("inf"), True)
        kps, scores, sig, meta_out = self._views()
        encode_people(people, signals, kps, scores, sig, meta_out)
        out, out_sig = decode_people(kps, scores, sig)
        assert out == people            # exact float equality
        assert out_sig == signals
        assert meta_out[6] == 0.0       # nothing truncated

    def test_roundtrip_no_signals_and_empty(self):
        kps, scores, sig, meta_out = self._views()
        encode_people([], None, kps, scores, sig, meta_out)
        out, out_sig = decode_people(kps, scores, sig)
        assert out == [] and out_sig is None

    def test_truncation_counted(self):
        people = [([(float(p), 1.0)] + [None] * (NUM_PARTS - 1), 1.0)
                  for p in range(1, 12)]
        kps, scores, sig, meta_out = self._views(max_people=8)
        encode_people(people, None, kps, scores, sig, meta_out)
        out, _ = decode_people(kps, scores, sig)
        assert len(out) == 8 and out == people[:8]
        assert meta_out[6] == 3.0


# --------------------------------------------------------------------- #
# one worker process behind the engine contract                         #
# --------------------------------------------------------------------- #
class TestProcessWorkerEngine:
    def test_serve_and_contract_refusals(self):
        with ProcessWorkerEngine(SPEC, {"num_parts": NUM_PARTS},
                                 **ENGINE_KW) as eng:
            with pytest.raises(DeadlineExceeded):
                eng.submit(_img(1), deadline_s=0.0)
            people, signals = eng.submit(
                _img(3), deadline_s=30.0).result(timeout=30)
            assert len(people) == 2 and signals.fused
            # deterministic content: base = img[0, 0, 0]
            assert people[0][0][0] == (3.0, 32.0)
            h = eng.health()
            assert h["running"] and h["dispatcher_alive"]
            assert h["fetchers_alive"] == h["fetchers_expected"] == 1
            assert _conserved(eng.metrics)
        with pytest.raises(RuntimeError, match="not running"):
            eng.submit(_img(1))

    def test_overload_sheds(self):
        kw = dict(ENGINE_KW, slots=2)
        with ProcessWorkerEngine(SPEC, {"num_parts": NUM_PARTS,
                                        "delay_s": 0.5}, **kw) as eng:
            futs = [eng.submit(_img(1)) for _ in range(2)]
            with pytest.raises(ServerOverloaded, match="in flight"):
                eng.submit(_img(1))
            assert eng.metrics.rejected == 1
            for f in futs:
                f.result(timeout=30)
            assert _conserved(eng.metrics)

    def test_worker_error_delivered_and_engine_survives(self):
        with ProcessWorkerEngine(SPEC, {"num_parts": NUM_PARTS,
                                        "fail_every": 2},
                                 **ENGINE_KW) as eng:
            eng.submit(_img(1)).result(timeout=30)        # call 1 ok
            with pytest.raises(RuntimeError,
                               match="injected predictor failure"):
                eng.submit(_img(1)).result(timeout=30)    # call 2 fails
            eng.submit(_img(1)).result(timeout=30)        # call 3 ok
            assert eng.metrics.failed == 1
            assert _conserved(eng.metrics)

    def test_deadline_expired_at_worker(self):
        with ProcessWorkerEngine(SPEC, {"num_parts": NUM_PARTS,
                                        "delay_s": 0.3},
                                 **ENGINE_KW) as eng:
            # first request holds the worker; the second's deadline
            # lapses while it waits in the task queue
            slow = eng.submit(_img(1), deadline_s=30.0)
            doa = eng.submit(_img(2), deadline_s=0.05)
            with pytest.raises(DeadlineExceeded):
                doa.result(timeout=30)
            slow.result(timeout=30)
            assert eng.metrics.expired == 1

    def test_sigkill_fails_inflight_and_respawn_serves(self):
        kw = dict(ENGINE_KW)
        with ProcessWorkerEngine(SPEC, {"num_parts": NUM_PARTS,
                                        "delay_s": 0.4}, **kw) as eng:
            fut = eng.submit(_img(1), deadline_s=30.0)
            time.sleep(0.05)
            os.kill(eng.worker_stats()["pid"], signal.SIGKILL)
            with pytest.raises(RuntimeError):   # WorkerDied
                fut.result(timeout=30)
            assert not eng.health()["running"]
            assert eng.consecutive_failures == 1
            # the pool's restart path: start() respawns with backoff
            eng.start()
            assert eng.health()["running"]
            eng.submit(_img(4)).result(timeout=30)
            assert eng.consecutive_failures == 0   # progress resets
            assert eng.restarts == 2
            assert _conserved(eng.metrics)

    def test_crash_budget_stops_the_respawn_loop(self):
        eng = ProcessWorkerEngine(SPEC, {"num_parts": NUM_PARTS},
                                  crash_budget=2, backoff_base_s=0.0,
                                  **ENGINE_KW)
        eng.consecutive_failures = 2       # deterministic crash loop
        eng.start()
        assert eng.gave_up and not eng.health()["running"]
        eng.stop()


# --------------------------------------------------------------------- #
# thread arm for the bit-identity check                                 #
# --------------------------------------------------------------------- #
class InlineEngine:
    """The SAME fake predictor served in-process on threads — the
    thread-pool arm of the bit-identity contract."""

    def __init__(self, **pred_kw):
        self.pred = constant_predictor(**pred_kw)
        self.metrics = ServeMetrics()
        self._running = False
        self._draining = False

    @property
    def draining(self):
        return self._draining

    def start(self):
        self._running = True
        return self

    def stop(self, drain_timeout_s=None):
        self._running = False

    def warmup(self, image_sizes, batch_sizes=None):
        return {}

    def submit(self, image, *, deadline_s=None):
        if not self._running:
            raise RuntimeError("not running")
        if deadline_s is not None and deadline_s <= 0:
            self.metrics.on_expire_rejected()
            raise DeadlineExceeded("expired at submit")
        self.metrics.on_submit()
        f = Future()
        try:
            f.set_result(self.pred.serve_one(image))
            self.metrics.on_complete(0.001)
        except Exception as e:  # noqa: BLE001 — delivered per request
            self.metrics.on_fail()
            f.set_exception(e)
        return f

    def health(self):
        return {"running": self._running, "draining": self._draining,
                "dispatcher_alive": self._running, "fetchers_alive": 1,
                "fetchers_expected": 1,
                "queue_depth": self.metrics.depth,
                "batches_in_flight": 0,
                "stall_age_s": self.metrics.stall_age_s()}


# --------------------------------------------------------------------- #
# fleet semantics                                                        #
# --------------------------------------------------------------------- #
class TestProcessRouter:
    def test_bit_identity_thread_vs_process_pool(self):
        """The process wire adds nothing and loses nothing: the same
        fake predictor behind a thread pool and behind worker processes
        yields bit-identical person tables and signals."""
        pred_kw = {"num_parts": NUM_PARTS, "n_people": 3}
        images = [_img(v, (32, 48)) for v in (0, 7, 19, 255)]
        with EnginePool([InlineEngine(**pred_kw),
                         InlineEngine(**pred_kw)]) as tpool:
            thread_res = [tpool.submit(im).result(timeout=10)
                          for im in images]
        with ProcessRouter(SPEC, num_workers=2, spec_kwargs=pred_kw,
                           **ENGINE_KW) as router:
            proc_res = [router.submit(im).result(timeout=30)
                        for im in images]
        assert thread_res == proc_res   # exact: floats, Nones, signals

    def test_kill_mid_flight_fails_over_and_respawns(self):
        with ProcessRouter(SPEC, num_workers=2,
                           spec_kwargs={"num_parts": NUM_PARTS,
                                        "delay_s": 0.25},
                           restart_after_s=0.3, probe_interval_s=0.05,
                           **ENGINE_KW) as router:
            futs = [router.submit(_img(v), deadline_s=60.0)
                    for v in range(6)]
            time.sleep(0.05)
            os.kill(router.workers[0].worker_stats()["pid"],
                    signal.SIGKILL)
            # zero lost futures: every one resolves WITH A RESULT (the
            # survivor absorbs the failovers)
            for f in futs:
                people, _ = f.result(timeout=60)
                assert len(people) == 2
            c = router.counters()
            assert c["fenced"] >= 1 and c["failovers"] >= 1
            deadline = time.time() + 20
            while time.time() < deadline:
                if router.counters()["restarts"] >= 1 and \
                        router.workers[0].health()["running"]:
                    break
                time.sleep(0.05)
            assert router.counters()["restarts"] >= 1
            # the respawned fleet serves new traffic
            router.submit(_img(9)).result(timeout=30)
            m = router.metrics
            assert _conserved(m) and m.failed == 0

    def test_drain_resolves_every_future(self):
        router = ProcessRouter(SPEC, num_workers=2,
                               spec_kwargs={"num_parts": NUM_PARTS,
                                            "delay_s": 0.15},
                               **ENGINE_KW).start()
        futs = [router.submit(_img(v), deadline_s=60.0)
                for v in range(8)]
        router.stop(drain_timeout_s=30.0)
        resolved = 0
        for f in futs:
            assert f.done()
            try:
                f.result(timeout=0)
                resolved += 1
            except Exception:  # noqa: BLE001 — typed error still counts
                resolved += 1
        assert resolved == len(futs)
        assert _conserved(router.metrics)

    def test_reqtrace_completeness_over_process_run(self, tmp_path):
        import sys

        from improved_body_parts_tpu.obs.events import (
            EventSink,
            NullSink,
            set_sink,
        )
        from improved_body_parts_tpu.obs.reqtrace import (
            ReqTrace,
            set_reqtrace,
        )

        path = str(tmp_path / "proc_events.jsonl")
        sink = EventSink(path)
        set_sink(sink)
        set_reqtrace(ReqTrace(sample=1.0))
        try:
            with ProcessRouter(SPEC, num_workers=2,
                               spec_kwargs={"num_parts": NUM_PARTS},
                               **ENGINE_KW) as router:
                futs = [router.submit(_img(v), deadline_s=30.0)
                        for v in range(10)]
                [f.result(timeout=30) for f in futs]
        finally:
            set_reqtrace(ReqTrace(sample=0.0))
            set_sink(NullSink())
            sink.close()
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import request_report

        records = request_report.load_records(path)
        summary = request_report.verify(records)
        assert summary["requests"] == 10
        assert summary["complete"], summary["violations"][:3]
        # the PR 3 multi-process sink rule: each worker wrote its own
        # `.pN` shard with its lifecycle events
        shards = sorted(p for p in os.listdir(tmp_path)
                        if p.startswith("proc_events.jsonl.p"))
        assert shards == ["proc_events.jsonl.p1",
                          "proc_events.jsonl.p2"]
        from improved_body_parts_tpu.obs.events import read_events

        for shard in shards:
            events = [e["event"] for e in
                      read_events(str(tmp_path / shard))]
            assert events[0] == "run_start"
            assert "worker_start" in events
