"""Tests: OKS evaluator + the final/AE model variants + end-to-end AP smoke.

The AP smoke test is the unit-level analogue of the reference's COCOeval
integration check (evaluate.py:616-621): decode GT-derived heatmaps of
planted people and demand AP == 1.0 against their annotations.
"""
import numpy as np
import pytest

from improved_body_parts_tpu.config import default_inference_params, get_config
from improved_body_parts_tpu.infer.decode import decode
from improved_body_parts_tpu.infer.oks import (
    average_precision,
    evaluate_oks,
    oks,
)

CFG = get_config("canonical")
SK = CFG.skeleton
PARAMS, _ = default_inference_params()


class TestOKS:
    def test_perfect_match(self):
        gt = np.zeros((17, 3))
        gt[:, 0] = np.arange(17) * 10
        gt[:, 1] = 50
        gt[:, 2] = 2
        det = gt[:, :2].copy()
        assert oks(det, gt, area=5000.0) == pytest.approx(1.0)

    def test_distance_decay(self):
        gt = np.zeros((17, 3))
        gt[:, 2] = 2
        near = np.full((17, 2), 5.0)
        far = np.full((17, 2), 50.0)
        assert oks(near, gt, 1000.0) > oks(far, gt, 1000.0)

    def test_unlabeled_ignored(self):
        gt = np.zeros((17, 3))
        gt[0] = [10, 10, 2]  # only the nose is labeled
        det = np.full((17, 2), 500.0)
        det[0] = [10, 10]
        assert oks(det, gt, 1000.0) == pytest.approx(1.0)

    def test_average_precision_extremes(self):
        # all detections true, covering all GT → AP 1
        s = np.array([0.9, 0.8, 0.7])
        assert average_precision(s, np.array([True] * 3), 3) == pytest.approx(
            1.0, abs=0.01)
        # all false → AP 0
        assert average_precision(s, np.array([False] * 3), 3) == 0.0

    def test_evaluate_oks_perfect(self):
        gt = np.zeros((17, 3))
        gt[:, 0] = np.arange(17) * 5 + 20
        gt[:, 1] = 60
        gt[:, 2] = 2
        gts = {1: [{"keypoints": gt, "area": 3000.0}]}
        dts = {1: [([tuple(p) for p in gt[:, :2]], 0.9)]}
        res = evaluate_oks(gts, dts)
        assert res["AP"] == pytest.approx(1.0, abs=0.01)
        assert res["AR"] == pytest.approx(1.0, abs=0.01)

    def test_evaluate_oks_false_positive_lowers_ap(self):
        gt = np.zeros((17, 3))
        gt[:, 0] = np.arange(17) * 5 + 20
        gt[:, 1] = 60
        gt[:, 2] = 2
        gts = {1: [{"keypoints": gt, "area": 3000.0}]}
        fp = [(float(x), 500.0) for x in range(17)]
        dts = {1: [([tuple(p) for p in gt[:, :2]], 0.5), (fp, 0.9)]}
        res = evaluate_oks(gts, dts)
        assert res["AP"] < 1.0


def _nose_gt(x, y, area=1000.0):
    """GT with only the nose labeled: OKS = exp(-d² / (2·area·(2σ_nose)²)),
    exactly invertible for analytic goldens."""
    gt = np.zeros((17, 3))
    gt[0] = [x, y, 2]
    return {"keypoints": gt, "area": area}


def _nose_det(x, y, score, target_oks=None, area=1000.0):
    """Detection displaced so oks() against _nose_gt(x, y) equals
    ``target_oks`` exactly (None = perfect)."""
    from improved_body_parts_tpu.infer.oks import COCO_SIGMAS

    if target_oks is not None:
        d = np.sqrt(-2.0 * area * (2 * COCO_SIGMAS[0]) ** 2
                    * np.log(target_oks))
        x = x + d
    coords = [None] * 17
    coords[0] = (float(x), float(y))
    return (coords, score)


class TestCOCOevalSemantics:
    """Analytic goldens for the discriminating COCOeval behaviours: the
    values below are derived by hand from the 101-point protocol, not from
    running this implementation (no pycocotools in this environment —
    see APCHECK.md)."""

    def test_imperfect_detections_analytic_ap(self):
        """2 GT; det1 OKS .72 (score .9), det2 OKS .92 (score .8), det3 FP
        (score .7).  Thr ≤ .70 (5 thrs): AP 1.  Thr .75-.90 (4 thrs):
        order is FP, TP, FP → PR (0, 1/2, 1/3) → interp 0.5 up to recall .5
        → AP = 51/101 · 0.5.  Thr .95: AP 0.
        """
        g1, g2 = _nose_gt(100, 100), _nose_gt(4000, 4000)
        dts = [
            _nose_det(100, 100, score=0.9, target_oks=0.72),
            _nose_det(4000, 4000, score=0.8, target_oks=0.92),
            _nose_det(9000, 9000, score=0.7),  # matches nothing
        ]
        res = evaluate_oks({1: [g1, g2]}, {1: dts})
        ap75 = 51 / 101 * 0.5
        assert res["AP50"] == pytest.approx(1.0)
        assert res["AP75"] == pytest.approx(ap75)
        assert res["AP"] == pytest.approx((5 * 1.0 + 4 * ap75) / 10)
        assert res["AR"] == pytest.approx((5 * 1.0 + 4 * 0.5) / 10)

    def test_crowd_region_absorbs_detections(self):
        """Detections inside a crowd GT's (doubly expanded) bbox are ignored
        — neither TP nor FP — and the crowd stays matchable for several
        detections; the crowd GT never counts toward recall."""
        crowd_kpts = np.zeros((17, 3))  # no labeled keypoints
        crowd = {"keypoints": crowd_kpts, "area": 4000.0, "iscrowd": 1,
                 "bbox": (500.0, 500.0, 100.0, 100.0)}
        real = _nose_gt(100, 100)
        # all 17 keypoints inside the crowd box: a missing keypoint encodes
        # as (0, 0), which lies OUTSIDE the expanded box and would dilute
        # the fallback OKS — same as handing pycocotools zero-filled slots
        in_crowd = [(550.0, 550.0)] * 17
        in_crowd2 = [(540.0, 560.0)] * 17
        dts = [
            (in_crowd, 0.95),            # would be the top-scored FP
            (in_crowd2, 0.9),            # crowd must absorb this one too
            _nose_det(100, 100, 0.8),    # perfect on the real GT
        ]
        res = evaluate_oks({1: [real, crowd]}, {1: dts})
        assert res["AP"] == pytest.approx(1.0)
        assert res["AR"] == pytest.approx(1.0)

    def test_ignored_gt_excluded_from_recall(self):
        gts = [_nose_gt(100, 100), dict(_nose_gt(4000, 4000), ignore=True)]
        dts = [_nose_det(100, 100, 0.9)]
        res = evaluate_oks({1: gts}, {1: dts})
        assert res["AP"] == pytest.approx(1.0)
        assert res["AR"] == pytest.approx(1.0)

    def test_max_dets_cap(self):
        """COCO keypoints keeps only the 20 highest-scored detections per
        image; a true positive ranked 21st must not count."""
        gts = [_nose_gt(100, 100)]
        dts = [_nose_det(5000 + 100 * i, 5000, 0.9 - 0.001 * i)
               for i in range(20)]
        dts.append(_nose_det(100, 100, 0.1))  # rank 21: dropped
        res = evaluate_oks({1: gts}, {1: dts})
        assert res["AP"] == 0.0
        assert res["AR"] == 0.0

    def test_oks_crowd_fallback_formula(self):
        """Inside the expanded box → distance 0 → OKS 1; outside decays by
        the distance past the border (COCOeval computeOks k1==0 branch)."""
        from improved_body_parts_tpu.infer.oks import oks

        crowd = np.zeros((17, 3))
        bbox = (0.0, 0.0, 100.0, 100.0)
        inside = np.full((17, 2), 150.0)   # within [−100, 200]
        assert oks(inside, crowd, 4000.0, bbox=bbox) == pytest.approx(1.0)
        outside = np.full((17, 2), 250.0)  # 50 px past both borders
        d2 = 50.0 ** 2 + 50.0 ** 2
        from improved_body_parts_tpu.infer.oks import COCO_SIGMAS

        expect = np.exp(-d2 / (2 * 4000.0 * (2 * COCO_SIGMAS) ** 2)).mean()
        assert oks(outside, crowd, 4000.0, bbox=bbox) == pytest.approx(
            float(expect))


class TestEndToEndAP:
    def test_decode_of_planted_people_reaches_ap_1(self):
        import sys

        sys.path.insert(0, "tests")
        from test_decode import synth_maps, synth_person_joints

        from improved_body_parts_tpu.config import COCO_PARTS

        people = [synth_person_joints(60, 80, 300),
                  synth_person_joints(300, 120, 260)]
        heat, paf = synth_maps(people)
        results = decode(heat, paf, PARAMS, SK, use_native=False)
        assert len(results) == 2

        gts = []
        for p in people:
            kp = np.zeros((17, 3))
            for ci, part in enumerate(COCO_PARTS):
                gi = SK.parts_dict[part]
                kp[ci] = [p[0, gi, 0], p[0, gi, 1], 2]
            xs, ys = kp[:, 0], kp[:, 1]
            area = (xs.max() - xs.min()) * (ys.max() - ys.min())
            gts.append({"keypoints": kp, "area": area})
        res = evaluate_oks({1: gts}, {1: results})
        assert res["AP"] == pytest.approx(1.0, abs=0.01), res
        assert res["AR"] == pytest.approx(1.0, abs=0.01)


class TestVariants:
    def test_final_variant_forward(self):
        import jax
        import jax.numpy as jnp

        from improved_body_parts_tpu.models import PoseNetFinal

        model = PoseNetFinal(nstack=2, inp_dim=16, oup_dim=8, increase=8,
                             hourglass_depth=2, se_reduction=4,
                             dtype=jnp.float32)
        imgs = jnp.zeros((1, 32, 32, 3))
        v = model.init(jax.random.PRNGKey(0), imgs, train=False)
        preds = model.apply(v, imgs, train=False)
        assert len(preds) == 2 and len(preds[0]) == 3
        assert preds[0][0].shape == (1, 8, 8, 8)

    def test_ae_variant_forward(self):
        import jax
        import jax.numpy as jnp

        from improved_body_parts_tpu.models import PoseNetAE

        model = PoseNetAE(nstack=2, inp_dim=16, oup_dim=8, increase=8,
                          hourglass_depth=2, dtype=jnp.float32)
        imgs = jnp.zeros((1, 32, 32, 3))
        v = model.init(jax.random.PRNGKey(0), imgs, train=False)
        preds = model.apply(v, imgs, train=False)
        # single full-resolution output per stack (ae_pose.py:50-56)
        assert len(preds) == 2 and len(preds[0]) == 1
        assert preds[0][0].shape == (1, 8, 8, 8)

    def test_ae_config_is_trainable(self):
        """The 'ae' registry config pairs the single-scale model with a
        single-entry scale_weight so the loss consumes its outputs."""
        import jax
        import jax.numpy as jnp

        from improved_body_parts_tpu.models import build_model
        from improved_body_parts_tpu.ops import multi_task_loss

        import dataclasses

        cfg = get_config("ae")
        assert cfg.train.scale_weight == (1.0,)
        cfg = cfg.replace(
            model=cfg.model.__class__(
                nstack=2, inp_dim=16, increase=8, hourglass_depth=2,
                variant="ae"),
            train=dataclasses.replace(cfg.train, nstack_weight=(1.0, 1.0)))
        model = build_model(cfg, dtype=jnp.float32)
        imgs = jnp.zeros((1, 32, 32, 3))
        v = model.init(jax.random.PRNGKey(0), imgs, train=False)
        preds = model.apply(v, imgs, train=False)
        gt = jnp.zeros((1, 8, 8, cfg.skeleton.num_layers))
        mask = jnp.ones((1, 8, 8, 1))
        loss = multi_task_loss(preds, gt, mask, cfg)
        assert np.isfinite(float(loss))

    @pytest.mark.slow
    def test_remat_via_config(self):
        # slow tier (PR 8 budget audit): 37 s — a full grad compile to
        # check config plumbing; remat correctness itself is
        # backend-enforced (identical math, different schedule)
        import jax
        import jax.numpy as jnp

        from improved_body_parts_tpu.models import build_model

        cfg = get_config("tiny")
        cfg = cfg.replace(model=cfg.model.__class__(
            nstack=2, inp_dim=16, increase=8, hourglass_depth=2,
            se_reduction=4, remat=True))
        model = build_model(cfg, dtype=jnp.float32)
        assert model.remat is True
        imgs = jax.random.uniform(jax.random.PRNGKey(0), (1, 32, 32, 3))
        v = model.init(jax.random.PRNGKey(0), imgs, train=False)

        def f(params):
            preds = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                imgs, train=False)
            return sum(jnp.sum(p ** 2) for s in preds for p in s)

        g = jax.grad(f)(v["params"])
        assert max(float(jnp.abs(x).max()) for x in jax.tree.leaves(g)) > 0

    def test_build_model_dispatches_all_variants(self):
        import jax
        import jax.numpy as jnp

        from improved_body_parts_tpu.models import build_model

        cfg = get_config("tiny")
        for variant in ("imhn", "imhn_final", "imhn_independent",
                        "imhn_light", "ae"):
            c = cfg.replace(model=cfg.model.__class__(
                nstack=1, inp_dim=16, increase=8, hourglass_depth=2,
                se_reduction=4, variant=variant))
            model = build_model(c, dtype=jnp.float32)
            shapes = jax.eval_shape(
                lambda k, m=model: m.init(k, jnp.zeros((1, 32, 32, 3)),
                                          train=False),
                jax.random.PRNGKey(0))
            assert shapes["params"]
        with pytest.raises(ValueError):
            bad = cfg.replace(model=cfg.model.__class__(variant="nope"))
            build_model(bad)


class TestAreaRangeSplits:
    """Analytic goldens for AP_M/AP_L (COCOeval area-range semantics):
    per range, out-of-range GTs are ignored; an UNMATCHED detection whose
    own (loadRes tight-keypoint-bbox) area is outside the range is ignored
    rather than counted as a false positive."""

    @staticmethod
    def _person(x0, y0, spread):
        gt = np.zeros((17, 3))
        gt[:, 0] = x0 + np.linspace(0, spread, 17)
        gt[:, 1] = y0 + (np.arange(17) % 4) * spread / 4
        gt[:, 2] = 2
        return gt

    def test_medium_large_splits_analytic(self):
        # medium GT (area 2500 in [32^2, 96^2]) and large GT (area 10^4)
        gt_m = self._person(100, 100, 40)
        gt_l = self._person(400, 100, 90)
        gts = {1: [{"keypoints": gt_m, "area": 2500.0},
                   {"keypoints": gt_l, "area": 10000.0}]}
        det = lambda g: [tuple(p) for p in g[:, :2]]  # noqa: E731
        # dC: highest-scored FALSE positive far from both GTs, with a
        # medium-sized keypoint bbox (spread 40 -> area 40*30 = 1200)
        d_c = self._person(800, 600, 40)
        dts = {1: [(det(d_c), 0.95),          # FP, medium-sized
                   (det(gt_m), 0.90),         # perfect on medium GT
                   (det(gt_l), 0.80)]}        # perfect on large GT

        m = evaluate_oks(gts, dts)
        # all: order FP,TP,TP -> precision [0,.5,2/3] -> monotone 2/3
        assert m["AP"] == pytest.approx(2 / 3, abs=1e-9)
        assert m["AR"] == pytest.approx(1.0)
        # medium: large GT ignored (its det too); the FP's own area is
        # in-range so it COUNTS -> order FP,TP -> precision .5 everywhere
        assert m["AP_M"] == pytest.approx(0.5, abs=1e-9)
        assert m["AR_M"] == pytest.approx(1.0)
        # large: medium GT ignored; the FP's area is OUTSIDE the large
        # range -> ignored, not an FP -> clean AP 1.0
        assert m["AP_L"] == pytest.approx(1.0)
        assert m["AR_L"] == pytest.approx(1.0)
        # the 10-stat summary is complete
        for key in ("AP", "AP50", "AP75", "AP_M", "AP_L",
                    "AR", "AR50", "AR75", "AR_M", "AR_L"):
            assert key in m

    def test_range_with_no_gt_is_nan(self):
        gt = self._person(100, 100, 40)
        gts = {1: [{"keypoints": gt, "area": 2500.0}]}  # medium only
        dts = {1: [([tuple(p) for p in gt[:, :2]], 0.9)]}
        m = evaluate_oks(gts, dts)
        assert np.isnan(m["AP_L"]) and np.isnan(m["AR_L"])
        assert m["AP_M"] == pytest.approx(1.0)
