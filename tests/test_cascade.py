"""Cascade serving (serve.cascade): escalation-predicate boundaries over
all three signal classes, the free-signals plumbing through the batcher,
end-to-end student/teacher routing (GatedPredictor-driven), degradation
semantics, both-tier warmup, and the routing metrics."""
import os
import sys
import threading
import time

import numpy as np
import pytest

from improved_body_parts_tpu.config import (
    InferenceModelParams,
    get_config,
)
from improved_body_parts_tpu.infer import Predictor
from improved_body_parts_tpu.infer.decode import (
    DeviceDecoded,
    EscalationSignals,
    device_signals,
)
from improved_body_parts_tpu.serve import (
    CascadeEngine,
    DynamicBatcher,
    EscalationPolicy,
    ServeMetrics,
    ServerOverloaded,
)
from improved_body_parts_tpu.serve.batcher import DeadlineExceeded

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))
from cascade_bench import TieredPlantedModel, plant_people  # noqa: E402

CFG = get_config("tiny")
SK = CFG.skeleton
SIZE = 128


def _sig(n_people=1, peak=False, cand=False, person=False,
         min_mean_score=2.0, fused=True):
    return EscalationSignals(n_people=n_people, peak_overflow=peak,
                             cand_overflow=cand, person_overflow=person,
                             min_mean_score=min_mean_score, fused=fused)


class TestEscalationPolicy:
    def test_person_count_boundary(self):
        p = EscalationPolicy(max_people=4)
        assert p.reason(_sig(n_people=4)) is None      # == stays
        assert p.reason(_sig(n_people=5)) == "people"  # > escalates
        assert p.reason(_sig(n_people=0)) is None

    def test_each_overflow_flag_escalates(self):
        p = EscalationPolicy(max_people=100)
        assert p.reason(_sig(peak=True)) == "overflow"
        assert p.reason(_sig(cand=True)) == "overflow"
        assert p.reason(_sig(person=True)) == "overflow"
        # disabled: the flags fall through to the other signals
        off = EscalationPolicy(max_people=100,
                               escalate_on_overflow=False)
        assert off.reason(_sig(peak=True, cand=True, person=True)) is None

    def test_score_floor_boundary(self):
        p = EscalationPolicy(max_people=100, score_floor=1.5)
        assert p.reason(_sig(min_mean_score=1.5)) is None  # == stays
        assert p.reason(_sig(min_mean_score=1.4999)) == "score"
        # floor 0 disables the signal entirely
        assert EscalationPolicy(max_people=100).reason(
            _sig(min_mean_score=0.0)) is None
        # nobody kept -> +inf score never trips the floor
        assert p.reason(_sig(n_people=0,
                             min_mean_score=float("inf"))) is None

    def test_overflow_outranks_people_and_score(self):
        p = EscalationPolicy(max_people=1, score_floor=1.5)
        sig = _sig(n_people=9, peak=True, min_mean_score=0.1)
        assert p.reason(sig) == "overflow"


def test_device_signals_reads_masked_people_only():
    """min_mean_score comes from KEPT (masked-in) rows only, and
    n_people/flags pass straight through."""
    n = SK.num_parts
    subset = np.zeros((4, n + 2, 2), np.float32)
    subset[0, n, 0], subset[0, n + 1, 0] = 6.0, 3.0   # mean 2.0
    subset[1, n, 0], subset[1, n + 1, 0] = 1.0, 2.0   # mean 0.5
    subset[2, n, 0], subset[2, n + 1, 0] = 0.1, 1.0   # pruned out
    mask = np.array([True, True, False, False])
    dev = DeviceDecoded(subset=subset, mask=mask, n_people=2,
                        peak_overflow=False, cand_overflow=True,
                        person_overflow=False, compact=None)
    sig = device_signals(dev)
    assert sig.n_people == 2
    assert sig.cand_overflow and not sig.peak_overflow
    assert sig.min_mean_score == pytest.approx(0.5)
    assert sig.fused is False  # cand_overflow -> not authoritative
    # nobody kept: the score signal reads +inf, not a crash
    empty = dev._replace(mask=np.zeros(4, bool), cand_overflow=False,
                         n_people=0)
    s2 = device_signals(empty)
    assert s2.min_mean_score == float("inf") and s2.fused is True


# ------------------------------------------------------------------ #
# real two-tier fixtures: flip-aware planted maps, brightness-selected
# (easy = 1 person, hard = 2) so the student's device payload separates
# the stream exactly

@pytest.fixture(scope="module")
def planted():
    rng = np.random.default_rng(3)
    easy_maps, easy_gt = plant_people(SK, 1, rng, SIZE)
    hard_maps, hard_gt = plant_people(SK, 2, rng, SIZE)
    return easy_maps, hard_maps


def _tier_pred(maps_pair):
    """A predictor whose decode payload reports 1 person on dark frames
    and len(hard) people on bright ones (honest tiny forward)."""
    from improved_body_parts_tpu.models import build_model

    import jax
    import jax.numpy as jnp

    easy_maps, hard_maps = maps_pair
    model = build_model(CFG)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, SIZE, SIZE, 3)), train=False)
    planted = TieredPlantedModel(model, easy_maps, hard_maps, SK)
    return Predictor(planted, variables, SK,
                     model_params=InferenceModelParams(
                         boxsize=SIZE, max_downsample=64), bucket=64)


@pytest.fixture(scope="module")
def student_pred(planted):
    easy_maps, hard_maps = planted
    return _tier_pred((easy_maps, hard_maps))


@pytest.fixture(scope="module")
def teacher_pred(planted):
    # the teacher "solves" hard frames: it always sees the easy map, so
    # a teacher-answered frame is distinguishable by its person count
    easy_maps, _ = planted
    return _tier_pred((easy_maps, easy_maps))


DARK = np.zeros((SIZE, SIZE, 3), np.uint8)
BRIGHT = np.full((SIZE, SIZE, 3), 255, np.uint8)


def test_emit_signals_plumbing(student_pred):
    """emit_signals=True resolves futures to (skeletons, signals) with
    the payload's free difficulty readout; the knob requires the
    device-decode lane."""
    with pytest.raises(ValueError):
        DynamicBatcher(student_pred, device_decode=False,
                       emit_signals=True)
    with DynamicBatcher(student_pred, max_batch=2,
                        emit_signals=True) as server:
        server.warmup([(SIZE, SIZE)], batch_sizes=(1,))
        skel_e, sig_e = server.submit(DARK).result(timeout=120)
        skel_h, sig_h = server.submit(BRIGHT).result(timeout=120)
    assert sig_e.fused and sig_h.fused
    assert sig_e.n_people == 1 and len(skel_e) == 1
    assert sig_h.n_people == 2 and len(skel_h) == 2


def test_easy_from_student_hard_from_teacher(student_pred, teacher_pred):
    """The tentpole routing claim: an easy frame's skeletons come from
    the STUDENT (1 planted person), a hard frame's from the TEACHER —
    whose always-easy maps make its answer (1 person) distinguishable
    from the student's own hard answer (2 people)."""
    cascade = CascadeEngine.build(student_pred, teacher_pred,
                                  policy=EscalationPolicy(max_people=1),
                                  max_batch=2)
    with cascade:
        cascade.warmup([(SIZE, SIZE)], batch_sizes=(1,))
        easy = cascade.submit(DARK).result(timeout=120)
        hard = cascade.submit(BRIGHT).result(timeout=120)
    assert len(easy) == 1
    # answered by the teacher: 1 person (the student itself would have
    # returned the hard map's 2)
    assert len(hard) == 1
    snap = cascade.metrics.snapshot()
    assert snap["answered_student"] == 1
    assert snap["escalated_teacher"] == 1
    assert snap["escalations"] == {"overflow": 0, "people": 1,
                                   "score": 0}
    assert snap["failed"] == 0 and snap["depth"] == 0
    # conservation across the routing split
    assert snap["submitted"] == (snap["answered_student"]
                                 + snap["escalated_teacher"]
                                 + snap["degraded_student_answer"]
                                 + snap["failed"] + snap["depth"])


def test_hard_frame_waits_on_the_gated_teacher(student_pred,
                                               teacher_pred):
    """GatedPredictor-driven proof the hard result really comes from the
    teacher's device path: with the teacher's dispatch gated shut, the
    escalated frame stays pending AFTER the student answered; opening
    the gate resolves it with the teacher's answer."""
    from test_serve import GatedPredictor

    gate = threading.Event()
    gate.set()  # open for warmup
    gated = GatedPredictor(teacher_pred, gate)
    cascade = CascadeEngine.build(student_pred, gated,
                                  policy=EscalationPolicy(max_people=1),
                                  max_batch=2)
    with cascade:
        cascade.warmup([(SIZE, SIZE)], batch_sizes=(1,))
        # easy traffic never touches the teacher: serve one with the
        # gate SHUT to prove it
        gate.clear()
        assert len(cascade.submit(DARK).result(timeout=120)) == 1
        fut = cascade.submit(BRIGHT)
        # the student's leg completes and escalates; the teacher's
        # dispatcher is parked at the gate, so the future must wait
        deadline = time.perf_counter() + 30
        while (cascade.metrics.snapshot()["escalations"]["people"] < 1
               and time.perf_counter() < deadline):
            time.sleep(0.01)
        assert cascade.metrics.snapshot()["escalations"]["people"] >= 1
        time.sleep(0.1)
        assert not fut.done()
        gate.set()
        assert len(fut.result(timeout=120)) == 1  # the teacher's answer


class _FakeTeacher:
    """Duck-typed teacher engine with scripted submit behavior."""

    def __init__(self, behavior):
        self.behavior = behavior
        self.emit_signals = False

    def start(self):
        return self

    def stop(self, drain_timeout_s=None):
        pass

    def warmup(self, *a, **kw):
        return {"newly_compiled": 0}

    def submit(self, image, deadline_s=None):
        return self.behavior(image, deadline_s)


def _student_server(student_pred):
    server = DynamicBatcher(student_pred, max_batch=2,
                            metrics=ServeMetrics(model="student"),
                            emit_signals=True)
    return server


def test_teacher_shed_degrades_to_student_answer(student_pred):
    """An escalation the teacher sheds delivers the STUDENT's answer —
    a deliberate quality degrade, never a failed request."""
    def shed(image, deadline_s=None):
        raise ServerOverloaded("teacher full")

    cascade = CascadeEngine(_student_server(student_pred),
                            _FakeTeacher(shed),
                            policy=EscalationPolicy(max_people=1))
    with cascade:
        cascade.student.warmup([(SIZE, SIZE)], batch_sizes=(1,))
        hard = cascade.submit(BRIGHT).result(timeout=120)
    assert len(hard) == 2  # the student's own (hard-map) people
    snap = cascade.metrics.snapshot()
    assert snap["degraded_student_answer"] == 1
    assert snap["escalated_teacher"] == 0 and snap["failed"] == 0


def test_teacher_failure_mid_flight_degrades_deadline_propagates(
        student_pred):
    from concurrent.futures import Future

    failures = {"n": 0}

    def fail_async(image, deadline_s=None):
        f = Future()
        failures["n"] += 1
        if failures["n"] == 1:
            f.set_exception(RuntimeError("teacher died mid-batch"))
        else:
            f.set_exception(DeadlineExceeded("too late"))
        return f

    cascade = CascadeEngine(_student_server(student_pred),
                            _FakeTeacher(fail_async),
                            policy=EscalationPolicy(max_people=1))
    with cascade:
        cascade.student.warmup([(SIZE, SIZE)], batch_sizes=(1,))
        # teacher error -> degrade to the student's answer
        assert len(cascade.submit(BRIGHT).result(timeout=120)) == 2
        # DeadlineExceeded -> propagates (the caller already gave up)
        with pytest.raises(DeadlineExceeded):
            cascade.submit(BRIGHT).result(timeout=120)
    snap = cascade.metrics.snapshot()
    assert snap["degraded_student_answer"] == 1
    assert snap["failed"] == 1


def test_warmup_covers_both_tiers_and_drain_rejects(student_pred,
                                                    teacher_pred):
    cascade = CascadeEngine.build(student_pred, teacher_pred,
                                  policy=EscalationPolicy(max_people=1),
                                  max_batch=2)
    with cascade:
        warm = cascade.warmup([(SIZE, SIZE)], batch_sizes=(1,))
        assert set(warm) == {"student", "teacher"}
        # module fixtures already compiled these shapes: a second pass
        # must find every program warm on BOTH tiers (the
        # zero-post-warmup-recompile property the bench gates on)
        again = cascade.warmup([(SIZE, SIZE)], batch_sizes=(1,))
        assert again["student"]["newly_compiled"] == 0
        assert again["teacher"]["newly_compiled"] == 0
    cascade._draining = True
    with pytest.raises(ServerOverloaded):
        cascade.submit(DARK)


def test_cascade_metrics_exposition_names():
    """The collector's samples ride the shared registry with lint-clean
    names and the per-reason label."""
    from improved_body_parts_tpu.obs import Registry
    from improved_body_parts_tpu.serve import CascadeMetrics

    reg = Registry()
    m = CascadeMetrics().register_into(reg)
    m.on_submit()
    m.on_escalate("people")
    m.on_answer("teacher")
    text = reg.prometheus()
    assert "cascade_submitted_total 1.0" in text
    assert 'cascade_escalations_total{reason="people"} 1.0' in text
    assert "cascade_escalated_teacher_total 1.0" in text
    assert "cascade_escalation_rate 1.0" in text


@pytest.mark.slow
def test_cascade_bench_cli(tmp_path):
    """tools/cascade_bench.py end-to-end on the synthetic tier pair:
    the artifact records the routing snapshot AND the exact two-tier
    conservation ledger (submitted == answered_student +
    escalated_teacher + failed + depth) with zero post-warmup
    recompiles -- the same ledger discipline the stream fast path
    extends to three tiers."""
    import json
    import subprocess

    out = tmp_path / "CASCADE_BENCH.json"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run(
        [sys.executable, os.path.join(repo, "tools",
                                      "cascade_bench.py"),
         "--size", "128", "--clients", "2", "--requests", "4",
         "--rounds", "1", "--max-batch", "2", "--out", str(out)],
        check=True, timeout=1500, env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    r = json.loads(out.read_text())
    cons = r["cascade_conservation"]
    assert cons["exact"] is True
    assert cons["submitted"] == (cons["answered_student"]
                                 + cons["escalated_teacher"]
                                 + cons["degraded_student_answer"]
                                 + cons["failed"] + cons["depth"])
    assert cons["submitted"] > 0 and cons["depth"] == 0
    assert r["recompiles_post_warmup"] == 0
    assert "cascade_routing" in r
