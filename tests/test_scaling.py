"""Multi-chip scaling evidence on the virtual CPU mesh.

The host has a single CPU core, so *wall-clock* weak scaling cannot be
demonstrated here (8 virtual devices timeshare one core; see
tools/scaling_test.py for the measurement protocol that runs on real chips).
What the virtual mesh CAN prove, and what this module pins:

1. **SPMD numerical equivalence**: the same global batch produces the same
   loss and parameter update on every mesh shape (1/2/4/8-way data parallel
   and a ('data','model') 4x2 mesh) — the gradient all-reduce + replicated
   update is exact, so scaling out cannot change training results.
2. **Collective structure**: the compiled train step on a sharded mesh
   contains the cross-replica all-reduce the gradient sync requires.
3. **Model-axis spatial sharding**: the inference forward accepts an input
   sharded over ('data','model') (height split over 'model', GSPMD halo
   exchange for convs) and matches the unsharded result.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from improved_body_parts_tpu.parallel import (
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
)
from improved_body_parts_tpu.train import make_train_step

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_training import _tiny_setup  # noqa: E402


def _batch(rng, n, cfg):
    images = np.asarray(rng.uniform(0, 1, (n, 32, 32, 3)), np.float32)
    labels = np.asarray(
        rng.uniform(0, 1, (n, 8, 8, cfg.skeleton.num_layers)), np.float32)
    mask = np.ones((n, 8, 8, 1), np.float32)
    return images, mask, labels


class TestCrossMeshEquivalence:
    @pytest.fixture(scope="class")
    def setup(self, eight_devices):
        cfg, model, opt, state = _tiny_setup()
        rng = np.random.default_rng(7)
        return cfg, model, opt, state, _batch(rng, 8, cfg)

    def _run(self, setup, mesh):
        cfg, model, opt, state, batch = setup
        state = jax.device_put(state, replicated(mesh))
        sharded = shard_batch(batch, mesh)
        step = make_train_step(model, cfg, opt, donate=False)
        new_state, loss = step(state, *sharded)
        first = jax.tree.leaves(new_state.params)[0]
        return float(loss), np.asarray(first)

    @pytest.mark.slow
    def test_loss_and_update_identical_across_mesh_shapes(self, setup):
        # slow tier since ISSUE 15's budget re-fit (60s: five mesh
        # shapes × compiled steps on a degraded 2-core host).  Tier-1
        # twins retained: test_training's 8-device SPMD step,
        # test_partition's partitioned-vs-single equivalence, and this
        # class's compiled-all-reduce check; bench.py's "scaling" key
        # and the slow tier still run the full cross-shape sweep.
        """Scaling out is semantically invisible: 1x1, 2x1, 4x1, 8x1 and
        4x2 meshes all produce the same loss and the same updated params
        for one global batch (the all-reduced gradient is exact)."""
        ref_loss, ref_params = self._run(setup, make_mesh(data=1, model=1))
        for data, model_ax in [(2, 1), (4, 1), (8, 1), (4, 2)]:
            loss, params = self._run(setup,
                                     make_mesh(data=data, model=model_ax))
            assert loss == pytest.approx(ref_loss, rel=2e-5), (data, model_ax)
            np.testing.assert_allclose(params, ref_params, atol=2e-6,
                                       err_msg=f"mesh {data}x{model_ax}")

    def test_compiled_step_contains_gradient_all_reduce(self, setup):
        cfg, model, opt, state, batch = setup
        mesh = make_mesh(data=8, model=1)
        state = jax.device_put(state, replicated(mesh))
        sharded = shard_batch(batch, mesh)
        step = make_train_step(model, cfg, opt, donate=False)
        compiled = jax.jit(step).lower(state, *sharded).compile()
        hlo = compiled.as_text()
        assert "all-reduce" in hlo, "gradient sync collective missing"


class TestSpatialSharding:
    def test_model_axis_height_shard_matches_unsharded(self, eight_devices):
        """Split the input height over the 'model' axis (spatial partition
        for very large inference inputs): GSPMD inserts the conv halo
        exchange and the result must match the unsharded forward."""
        cfg, model, opt, state = _tiny_setup()
        mesh = make_mesh(data=2, model=2)
        variables = {"params": state.params,
                     "batch_stats": state.batch_stats}
        rng = np.random.default_rng(3)
        imgs = np.asarray(rng.uniform(0, 1, (2, 64, 64, 3)), np.float32)

        def fwd(variables, x):
            return model.apply(variables, x, train=False)[-1][0]

        plain = np.asarray(jax.jit(fwd)(variables, jnp.asarray(imgs)))

        spatial = NamedSharding(mesh, P("data", "model", None, None))
        x_sharded = jax.device_put(imgs, spatial)
        v_repl = jax.device_put(variables, replicated(mesh))
        out = np.asarray(jax.jit(fwd)(v_repl, x_sharded))
        np.testing.assert_allclose(out, plain, atol=2e-5)
