"""Smoke test of the learn→AP benchmark tool (tools/synth_ap.py).

Tiny scale: the full orchestration (drawn corpus → train CLI → fresh +
trained checkpoints → evaluate CLI with --boxsize → SYNTH_AP-style JSON)
must run and produce a well-formed artifact; the AP VALUE is only
asserted to be a finite number in [0, 1] — learning quality at this scale
is not the point (SYNTH_AP.json records the real 60-epoch result).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_synth_ap_tool_end_to_end(tmp_path):
    out = tmp_path / "ap.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "synth_ap.py"),
         "--train-images", "6", "--val-images", "2", "--epochs", "2",
         "--workdir", str(tmp_path / "work"), "--out", str(out)],
        capture_output=True, text=True, env=env, timeout=900,
        cwd=str(tmp_path))
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    result = json.loads(out.read_text())
    assert result["epochs"] == 2
    assert result["train_records"] > 0 and result["val_persons"] > 0
    for key in ("ap_trained", "ap_untrained"):
        assert 0.0 <= result[key] <= 1.0, (key, result[key])
    # the loss log was parsed from the real train CLI's epoch log
    assert result["train_loss_first"] is not None
    assert result["train_loss_last"] is not None
    # artifacts stayed inside the workdir (the --dump-name regression)
    assert not (tmp_path / "results").exists()
    assert (tmp_path / "work" / "results").is_dir()


def test_committed_dtype_matrix_artifact():
    """ISSUE 20 acceptance: the committed SYNTH_AP_DTYPE.json proves
    the int8 serve path lands within 1 synthetic-AP point of bf16 on
    the trained protocol (same checkpoint, same val set, only the
    serve-time weight storage differs)."""
    doc = json.load(open(os.path.join(REPO, "SYNTH_AP_DTYPE.json")))
    for key in ("ap_trained", "ap_trained_bf16", "ap_trained_int8"):
        assert 0.0 < doc[key] <= 1.0, (key, doc[key])
    assert doc["ap_untrained"] == 0.0
    assert doc["int8_ap_tolerance"] == 0.01
    delta = abs(doc["ap_trained_int8"] - doc["ap_trained_bf16"])
    assert delta <= doc["int8_ap_tolerance"]
    assert round(delta, 6) == doc["int8_vs_bf16_ap_delta"]
    assert doc["int8_within_tolerance"] is True
