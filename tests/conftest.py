"""Test harness: force JAX onto a virtual 8-device CPU mesh.

This is the TPU-native answer to "multi-node testing without a cluster"
(SURVEY.md §4): every sharding/collective test runs against 8 host devices via
``--xla_force_host_platform_device_count`` so pjit/shard_map programs compile
and execute exactly as they would across chips.

Must run before the first ``import jax`` anywhere in the test session.
"""
import os
import sys

# Force CPU even when the launch environment pins JAX_PLATFORMS to a real
# accelerator (the TPU is exclusive — concurrent test runs would deadlock on
# the device, and tests must not occupy it).
os.environ["JAX_PLATFORMS"] = "cpu"
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")
    import jax

    # The env assignment above is too late when sitecustomize has already
    # imported jax (it does in the TPU-tunnel environment, with
    # JAX_PLATFORMS=axon); force_cpu re-pins via jax.config (honoured before
    # first backend use) and asserts the pin actually took effect.
    from improved_body_parts_tpu.utils.platform import force_cpu

    force_cpu(8)

    # Persistent compilation cache makes repeated CPU test runs fast.
    cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices
