"""Test harness: force JAX onto a virtual 8-device CPU mesh.

This is the TPU-native answer to "multi-node testing without a cluster"
(SURVEY.md §4): every sharding/collective test runs against 8 host devices via
``--xla_force_host_platform_device_count`` so pjit/shard_map programs compile
and execute exactly as they would across chips.

Must run before the first ``import jax`` anywhere in the test session.
"""
import os
import sys

# Force CPU even when the launch environment pins JAX_PLATFORMS to a real
# accelerator (the TPU is exclusive — concurrent test runs would deadlock on
# the device, and tests must not occupy it).
os.environ["JAX_PLATFORMS"] = "cpu"
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


# Modules whose tests are compile-heavy (measured with --durations=0 on a
# 1-core host): excluded from the `-m quick` tier so `pytest -m quick`
# finishes in ~2 minutes there.  Everything not listed (and not marked
# `slow` or named in _HEAVY_TESTS) is marked `quick` automatically in
# pytest_collection_modifyitems.
_HEAVY_MODULES = frozenset({
    "test_cli_journey.py",      # 340s: full train->resume->evaluate CLI run
    "test_coco_journey.py",     # COCO JSON->corpus->train->evaluate CLI run
    "test_scaling.py",          # 330s: 5 mesh shapes x compiled train steps
    "test_synth_ap.py",         # 200s: whole synth_ap orchestration
    "test_graft_entry.py",      # 190s: dryrun_multichip compiles 2x
    "test_gt_device.py",        # 125s: device-GT vs host-label train steps
    "test_oks_and_variants.py", # 116s: every model variant forward
    "test_learning.py",         # 82s: real overfit run
    "test_serve.py",            # compiles compact batch programs for
                                # several (bucket x batch-size) combos
    "test_serve_pool.py",       # pool integration arm shares test_serve's
                                # stub-predictor compiles (per-replica)
    "test_checkpoint_async.py", # real donated train-step compile + a
                                # SIGKILLed subprocess + many orbax writes
    "test_supervisor.py",       # chaos smoke = several full train.py
                                # subprocesses; topology subprocess pair
    "test_program_audit.py",    # registry sweep traces every shipped
                                # program (eval_shape of the full state)
    "test_partition.py",        # compiles the GSPMD-partitioned train
                                # step on 4x2 / 2x2 meshes + spawns a
                                # ring worker
})
# Individually heavy tests inside otherwise-quick modules.
_HEAVY_TESTS = frozenset({
    "test_models.py::test_bf16_compute_keeps_fp32_params",
    "test_training.py::TestTrainStep::test_curriculum_resolution_resume",
    "test_training.py::TestTrainStep::test_spmd_step_on_8_device_mesh",
    "test_training.py::TestTrainStep::test_checkpoint_roundtrip",
    "test_compact.py::test_compact_under_spatial_mesh_matches_plain",
})


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")
    config.addinivalue_line(
        "markers", "quick: fast tier — `pytest -m quick` stays ~2 min on "
        "one core (auto-applied; see _HEAVY_MODULES)")
    import jax

    # The env assignment above is too late when sitecustomize has already
    # imported jax (it does in the TPU-tunnel environment, with
    # JAX_PLATFORMS=axon); force_cpu re-pins via jax.config (honoured before
    # first backend use) and asserts the pin actually took effect.
    from improved_body_parts_tpu.utils.platform import force_cpu

    force_cpu(8)

    # Persistent compilation cache makes repeated CPU test runs fast.
    cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def pytest_collection_modifyitems(config, items):
    """Auto-mark the quick tier: every test whose module is not
    compile-heavy, which is not individually heavy, and which is not
    explicitly marked ``slow``.

    Heavy-list entries are exact strings; a rename/move/parametrization
    would silently drop a listed test back into the quick tier and blow
    the ~2-minute budget, so stale entries that matched nothing in a full
    collection fail loudly here.
    """
    seen_modules, seen_tests = set(), set()
    for item in items:
        path, _, rest = item.nodeid.partition("::")
        module = path.rsplit("/", 1)[-1]
        # parametrized ids ("test_x[case]") still match their listed base
        base = f"{module}::{rest.partition('[')[0]}"
        seen_modules.add(module)
        seen_tests.add(base)
        if (module not in _HEAVY_MODULES
                and base not in _HEAVY_TESTS
                and "slow" not in item.keywords):
            item.add_marker(pytest.mark.quick)
    # only a full, unfiltered collection can prove an entry stale
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    whole_suite = all(
        os.path.abspath(a) in (tests_dir, os.path.dirname(tests_dir))
        for a in config.args)
    filtered = any(
        getattr(config.option, opt, None)
        for opt in ("keyword", "markexpr", "ignore", "ignore_glob",
                    "deselect", "lf", "last_failed", "ff", "failed_first"))
    if whole_suite and not filtered:
        # a module FILE that exists but collected zero items had a
        # COLLECTION ERROR — let pytest report that, don't misdiagnose it
        # as stale; a missing file, or an uncollected test inside a
        # collected module, IS stale (renamed/deleted)
        def _module_errored(module):
            return (module not in seen_modules
                    and os.path.exists(os.path.join(tests_dir, module)))

        stale = [m for m in sorted(_HEAVY_MODULES - seen_modules)
                 if not _module_errored(m)]
        stale += [t for t in sorted(_HEAVY_TESTS - seen_tests)
                  if not _module_errored(t.partition("::")[0])]
        if stale:
            raise pytest.UsageError(
                "conftest heavy-tier entries matched no collected test "
                f"(renamed or removed?): {stale}")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices
