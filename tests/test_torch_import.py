"""Torch→Flax weight import, verified by FORWARD-OUTPUT parity.

Builds the reference PoseNet (torch, random weights), converts its state_dict
with tools.import_torch_checkpoint, and compares every stack/scale output of
the two frameworks on the same input — the strongest architecture-fidelity
check available: identical numerics, not just identical parameter counts.
"""
import os
import sys
import types

import numpy as np
import pytest

# module-level guards so a host without torch OR without the reference
# checkout COLLECTS cleanly (skips) instead of erroring: the parity
# fixture imports the reference's torch PoseNet from /root/reference,
# which only exists on hosts provisioned with the upstream repo
torch = pytest.importorskip("torch")
if not os.path.isfile("/root/reference/models/posenet.py"):
    pytest.skip("reference repo not available at /root/reference "
                "(forward-parity needs the upstream torch PoseNet)",
                allow_module_level=True)


@pytest.fixture(scope="module")
def reference_posenet():
    sys.path.insert(0, "/root/reference")
    # the reference imports torchvision.densenet but never uses it
    if "torchvision" not in sys.modules:
        tv = types.ModuleType("torchvision")
        tvm = types.ModuleType("torchvision.models")
        tvm.densenet = None
        tv.models = tvm
        sys.modules["torchvision"] = tv
        sys.modules["torchvision.models"] = tvm
    from models.posenet import PoseNet as TorchPoseNet

    return TorchPoseNet


def test_forward_parity_small(reference_posenet):
    import jax
    import jax.numpy as jnp

    from improved_body_parts_tpu.models import PoseNet
    from tools.import_torch_checkpoint import convert_posenet_state_dict

    # the reference Backbone hardcodes its 256-channel output, so parity must
    # run at the real width; two stacks exercise the cross-stack merge mapping
    nstack, inp_dim, oup_dim, increase = 2, 256, 50, 128
    tmodel = reference_posenet(nstack, inp_dim, oup_dim, bn=True,
                               increase=increase, init_weights=False)
    # randomize beyond the default init so parity is non-trivial
    gen = torch.Generator().manual_seed(0)
    with torch.no_grad():
        for p in tmodel.parameters():
            p.copy_(torch.randn(p.shape, generator=gen) * 0.05)
        for name, b in tmodel.named_buffers():
            if name.endswith("running_mean"):
                b.copy_(torch.randn(b.shape, generator=gen) * 0.01)
            elif name.endswith("running_var"):
                b.copy_(1.0 + 0.1 * torch.rand(b.shape, generator=gen))
    tmodel.eval()

    params, stats = convert_posenet_state_dict(tmodel.state_dict(), nstack)

    fmodel = PoseNet(nstack=nstack, inp_dim=inp_dim, oup_dim=oup_dim,
                     increase=increase, hourglass_depth=4, se_reduction=16,
                     dtype=jnp.float32)
    rng = np.random.default_rng(1)
    img = rng.uniform(0, 1, (1, 64, 64, 3)).astype(np.float32)

    with torch.no_grad():
        t_out = tmodel(torch.from_numpy(img))
    f_out = fmodel.apply({"params": params, "batch_stats": stats},
                         jnp.asarray(img), train=False)

    assert len(t_out) == len(f_out) == nstack
    for i in range(nstack):
        assert len(t_out[i]) == len(f_out[i]) == 5
        for j in range(5):
            want = t_out[i][j].numpy().transpose(0, 2, 3, 1)  # NCHW → NHWC
            got = np.asarray(f_out[i][j])
            assert got.shape == want.shape, (i, j)
            np.testing.assert_allclose(
                got, want, atol=2e-4,
                err_msg=f"stack {i} scale {j}")


def test_converter_rejects_incomplete_state_dict(reference_posenet):
    from tools.import_torch_checkpoint import convert_posenet_state_dict

    tmodel = reference_posenet(1, 32, 10, bn=True, increase=16,
                               init_weights=False)
    sd = tmodel.state_dict()
    sd["bogus.extra.weight"] = torch.zeros(1)
    with pytest.raises(AssertionError, match="unmapped"):
        convert_posenet_state_dict(sd, 1)
