"""Golden-value tests for the config system.

The index tables here are the reference's *asserted* constants
(config/config.py:87-92 for limb indices, :121-124 for flip orders,
:117-118 for dt_gt_mapping); our configs derive them from name tables, so
these tests prove the derivation reproduces the reference layout exactly.
"""
import numpy as np
import pytest

from improved_body_parts_tpu.config import (
    available_configs,
    default_inference_params,
    get_config,
)

GOLDEN_LIMB_FROM = [1, 1, 1, 1, 1, 0, 0, 14, 15, 1, 2, 3, 1, 5, 6, 1, 8, 9, 1,
                    11, 12, 0, 0, 2, 8, 5, 11, 16, 17, 8]
GOLDEN_LIMB_TO = [0, 14, 15, 16, 17, 14, 15, 16, 17, 2, 3, 4, 5, 6, 7, 8, 9,
                  10, 11, 12, 13, 2, 5, 8, 12, 11, 9, 2, 5, 11]
GOLDEN_FLIP_HEAT = [0, 1, 5, 6, 7, 2, 3, 4, 11, 12, 13, 8, 9, 10, 15, 14, 17,
                    16, 18, 19]
GOLDEN_FLIP_PAF = [0, 2, 1, 4, 3, 6, 5, 8, 7, 12, 13, 14, 9, 10, 11, 18, 19,
                   20, 15, 16, 17, 22, 21, 25, 26, 23, 24, 28, 27, 29]
GOLDEN_DT_GT = {0: 0, 1: None, 2: 6, 3: 8, 4: 10, 5: 5, 6: 7, 7: 9, 8: 12,
                9: 14, 10: 16, 11: 11, 12: 13, 13: 15, 14: 2, 15: 1, 16: 4,
                17: 3}


def test_canonical_channel_layout():
    cfg = get_config("canonical")
    sk = cfg.skeleton
    assert sk.num_parts == 18
    assert sk.paf_layers == 30
    assert sk.heat_layers == 18
    assert sk.num_layers == 50
    assert sk.paf_start == 0
    assert sk.heat_start == 30
    assert sk.bkg_start == 48
    assert sk.grid_shape == (128, 128)
    assert sk.parts_shape == (128, 128, 50)
    assert sk.paf_thre == 4.0


def test_canonical_limb_indices_match_reference():
    sk = get_config("canonical").skeleton
    assert [f for f, _ in sk.limbs_conn] == GOLDEN_LIMB_FROM
    assert [t for _, t in sk.limbs_conn] == GOLDEN_LIMB_TO


def test_canonical_flip_orders_match_reference():
    sk = get_config("canonical").skeleton
    assert list(sk.flip_heat_ord) == GOLDEN_FLIP_HEAT
    assert list(sk.flip_paf_ord) == GOLDEN_FLIP_PAF


def test_canonical_dt_gt_mapping():
    sk = get_config("canonical").skeleton
    assert sk.dt_gt_mapping == GOLDEN_DT_GT


def test_three_stack_variant():
    cfg = get_config("three_stack_384")
    sk = cfg.skeleton
    assert sk.paf_layers == 24
    assert sk.num_layers == 44
    assert (sk.width, sk.height) == (384, 384)
    assert cfg.model.nstack == 3
    assert cfg.train.scale_weight == (0.2, 0.1, 0.4, 1.0, 4.0)
    # golden from config2.py (extracted from the reference module)
    assert [f for f, _ in sk.limbs_conn] == \
        [1, 1, 1, 1, 1, 0, 0, 14, 15, 1, 2, 3, 1, 5, 6, 1, 8, 9, 1, 11, 12, 8, 2, 5]
    assert list(sk.flip_paf_ord) == \
        [0, 2, 1, 4, 3, 6, 5, 8, 7, 12, 13, 14, 9, 10, 11, 18, 19, 20, 15, 16,
         17, 21, 23, 22]


def test_dense_variant():
    cfg = get_config("dense_384")
    sk = cfg.skeleton
    assert sk.paf_layers == 49
    assert sk.num_layers == 69
    assert cfg.model.inp_dim == 384 and cfg.model.increase == 192
    # flip orders golden from config_dense.py
    assert list(sk.flip_heat_ord) == \
        [0, 1, 5, 6, 7, 2, 3, 4, 11, 12, 13, 8, 9, 10, 16, 17, 14, 15, 18, 19]
    assert list(sk.flip_paf_ord) == \
        [0, 3, 4, 1, 2, 7, 8, 5, 6, 10, 9, 11, 15, 16, 17, 12, 13, 14, 20, 21,
         18, 19, 22, 25, 26, 23, 24, 30, 31, 32, 27, 28, 29, 33, 35, 34, 39,
         40, 41, 36, 37, 38, 42, 46, 47, 48, 43, 44, 45]


def test_final_variant():
    cfg = get_config("final_384")
    assert cfg.model.variant == "imhn_final"
    tp = cfg.skeleton.transform_params
    assert (tp.scale_min, tp.scale_max, tp.max_rotate_degree) == (0.6, 1.5, 50.0)


def test_registry():
    assert set(available_configs()) >= {
        "canonical", "three_stack_384", "dense_384", "final_384"}
    with pytest.raises(KeyError):
        get_config("nope")


def test_inference_params():
    params, model_params = default_inference_params()
    assert params.thre1 == 0.1 and params.thre2 == 0.1
    assert params.connect_ration == 0.8 and params.mid_num == 20
    assert params.len_rate == 16.0 and params.connection_tole == 0.7
    assert model_params.boxsize == 640 and model_params.max_downsample == 64
    assert model_params.pad_value == 128
