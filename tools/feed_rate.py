#!/usr/bin/env python
"""Input-pipeline feed rate vs the chip's training consumption rate.

The reference feeds ~40 samples/s per DataLoader worker process
(reference: README.md:35, data/mydataset.py:42-63) and scales by adding
workers (train_distributed.py:205-213).  This tool measures OUR pipeline's
per-process rate on the flagship 512-pixel protocol — both label modes —
through the REAL feed path (``data.batches`` → ``parallel.device_prefetch``
→ a device sink), then answers the capacity question SURVEY.md §7f asks:
how many host worker processes keep one chip (and a v5e-8 slice) fed at
the audited batch-8 train rate?

Label modes measured:
- host-GT: the full (image, mask, 50-channel label) synthesis on the host
  (the reference's protocol);
- device-GT (``--device-gt`` training): the host ships only
  (image, masks, padded joints) and the 50-channel tensor is synthesized
  inside the jitted train step (``ops.make_gt_synthesizer``) — the
  designed answer for pod-slice feeding, measured here as the host-side
  cost it actually leaves behind.

Writes one JSON artifact (``--out``, default INPUT_PIPELINE.json).

Note on this container: with a single host core, multi-worker rows
timeshare one core (ROADMAP documents the same ceiling for the scaling
tests), so worker counts are projected from the measured per-process rate
rather than demonstrated; on a real TPU host the same tool reports
demonstrated rates.
"""
import argparse
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_epochs(ds, batch_size, num_workers, raw_gt, mesh, min_seconds,
                   device_sink=True):
    """Samples/s through batches() -> device_prefetch -> blocking sink."""
    from improved_body_parts_tpu.data.dataset import batches
    from improved_body_parts_tpu.parallel import device_prefetch

    import jax

    n = 0
    t0 = time.perf_counter()
    epoch = 0
    while True:
        it = batches(ds, batch_size, epoch, num_workers=num_workers,
                     raw_gt=raw_gt)
        if device_sink:
            it = device_prefetch(it, mesh)
        for batch in it:
            jax.block_until_ready(batch)
            n += batch[0].shape[0]
        epoch += 1
        dt = time.perf_counter() - t0
        if dt >= min_seconds:
            return n / dt, n, dt


def main():
    ap = argparse.ArgumentParser(
        description="input pipeline feed-rate benchmark (SURVEY.md 7f)")
    ap.add_argument("--config", default="canonical",
                    help="the 512-pixel flagship protocol by default")
    ap.add_argument("--records", type=int, default=48)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--min-seconds", type=float, default=20.0,
                    help="measure at least this long per row")
    ap.add_argument("--workers", default="0,1,2",
                    help="comma-separated worker counts (0 = synchronous)")
    ap.add_argument("--max-people", type=int, default=8,
                    help="joint padding for the device-GT payload")
    ap.add_argument("--train-rate", type=float, default=0.0,
                    help="chip train consumption in imgs/s; 0 reads "
                         "TRAIN_BENCH.json (audited b8 step rate)")
    ap.add_argument("--out", default="INPUT_PIPELINE.json")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import jax

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.data import build_fixture
    from improved_body_parts_tpu.data.dataset import CocoPoseDataset
    from improved_body_parts_tpu.parallel import make_mesh

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    train_rate = args.train_rate
    if not train_rate:
        try:
            with open(os.path.join(repo, "TRAIN_BENCH.json")) as f:
                audit = json.load(f)["batches"]
            # the largest audited train batch (b8: 71.75 imgs/s on-chip)
            train_rate = max(
                (float(v["imgs_per_sec"]) for v in audit.values()))
        except Exception:  # artifact absent — fall back to the known figure
            train_rate = 71.75

    cfg = get_config(args.config)
    mesh = make_mesh()
    size = cfg.skeleton.height

    with tempfile.TemporaryDirectory(prefix="feed_rate_") as work:
        corpus = os.path.join(work, "corpus.h5")
        n_rec = build_fixture(corpus, num_images=args.records,
                              people_per_image=2,
                              img_size=(size * 3 // 4, size),
                              image_size=size, seed=0, drawn=True)
        ds = CocoPoseDataset(corpus, cfg, augment=True)
        print(f"corpus: {n_rec} records at {size}px; chip rate target "
              f"{train_rate:.1f} imgs/s", flush=True)

        rows = []
        for mode, raw_gt in (("host_gt", 0), ("device_gt", args.max_people)):
            for w in [int(x) for x in args.workers.split(",")]:
                rate, n, dt = measure_epochs(
                    ds, args.batch, w, raw_gt, mesh, args.min_seconds)
                rows.append({"mode": mode, "workers": w,
                             "samples_per_sec": round(rate, 2),
                             "samples": n, "seconds": round(dt, 2)})
                print(f"{mode} workers={w}: {rate:.2f} samples/s "
                      f"({n} in {dt:.1f}s)", flush=True)

        # capacity projection from the best measured PER-PROCESS rate
        # (sync row — pool rows on a 1-core host timeshare the same core)
        per_proc = {m: max(r["samples_per_sec"] for r in rows
                           if r["mode"] == m and r["workers"] == 0)
                    for m in ("host_gt", "device_gt")}
        projection = {
            m: {"per_process_rate": per_proc[m],
                "workers_for_one_chip": math.ceil(train_rate / per_proc[m]),
                "workers_for_v5e8": math.ceil(8 * train_rate / per_proc[m])}
            for m in per_proc}

        result = {
            "config": args.config, "image_size": size, "batch": args.batch,
            "platform": jax.devices()[0].platform,
            "host_cores": os.cpu_count(),
            "chip_train_rate_imgs_per_sec": train_rate,
            "protocol": "data.batches -> parallel.device_prefetch -> "
                        "block_until_ready sink; drawn fixture corpus; "
                        "augment on",
            "rows": rows,
            "projection": projection,
        }
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(json.dumps(result))


if __name__ == "__main__":
    main()
