#!/usr/bin/env python
"""Input-pipeline feed rate vs the chip's training consumption rate.

The reference feeds ~40 samples/s per DataLoader worker process
(reference: README.md:35, data/mydataset.py:42-63) and scales by adding
workers (train_distributed.py:205-213).  This tool measures OUR pipeline's
rate on the flagship 512-pixel protocol — both label modes — through the
REAL feed path (batch source → ``parallel.device_prefetch`` → a device
sink), then answers the capacity question SURVEY.md §7f asks: how many
host worker processes keep one chip (and a v5e-8 slice) fed at the audited
batch-8 train rate?

Batch sources measured per mode (host-GT / device-GT):
- ``sync``  (workers=0): in-process generation — the per-process baseline;
- ``shm``   (workers≥1): the persistent shared-memory ring
  (``data.shm_ring``) — spawn cost is paid once, excluded from the
  steady-state window; only slot tokens cross process boundaries, and
  with the uint8 wire images cross host→device 4x smaller;
- ``pool``  (optional, ``--pipelines sync,shm,pool``): the RETIRED
  spawn-Pool path kept as an A/B reference — every sample crossed the
  Pool pipe as ~6 MB of pickled fp32, which made workers 4-6x slower
  than sync (the PR-1-era INPUT_PIPELINE.json rows this PR replaces).

Writes one JSON artifact (``--out``, default INPUT_PIPELINE.json).

Worker counts above the host's core count timeshare cores; the projection
block scales the measured per-worker steady-state rate to the worker
counts a real multi-core TPU host would run.
"""
import argparse
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


def measure(make_iter, batch_size, mesh, min_seconds, device_sink=True,
            abandonable=True):
    """Samples/s through make_iter(epoch) -> device_prefetch -> blocking
    sink, for at least ``min_seconds``.

    ``make_iter(epoch)`` may return a finite per-epoch iterator (sync /
    pool rows: re-invoked per epoch, paying any per-epoch bubble) or an
    endless one (the shm row passes ``lambda _: ring.stream()``, the
    cross-epoch-pipelined steady state).  ``abandonable=True`` closes the
    window at the next batch boundary (prefetch joins its producer, the
    ring reclaims in-flight slots); the pool path must run
    ``abandonable=False`` — whole epochs only — because abandoning it
    mid-epoch raises GeneratorExit inside its ``with Pool`` block and
    ``Pool.terminate()`` can deadlock on in-flight async results.
    """
    from improved_body_parts_tpu.parallel import device_prefetch

    import jax

    n = 0
    t0 = time.perf_counter()
    epoch = 0
    while True:
        it = make_iter(epoch)
        if device_sink:
            it = device_prefetch(it, mesh)
        try:
            for batch in it:
                jax.block_until_ready(batch)
                n += batch[0].shape[0]
                if abandonable and \
                        time.perf_counter() - t0 >= min_seconds:
                    break
        finally:
            if abandonable and hasattr(it, "close"):
                it.close()
        epoch += 1
        dt = time.perf_counter() - t0
        if dt >= min_seconds:
            return n / dt, n, dt


def main():
    ap = argparse.ArgumentParser(
        description="input pipeline feed-rate benchmark (SURVEY.md 7f)")
    ap.add_argument("--config", default="canonical",
                    help="the 512-pixel flagship protocol by default")
    ap.add_argument("--records", type=int, default=48)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--min-seconds", type=float, default=20.0,
                    help="measure at least this long per row (split across "
                         "--repeats interleaved passes)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="interleaved measurement rounds per row (the "
                         "serve_bench verdict-round protocol): round-robin "
                         "through every row per round so host-load noise "
                         "hits all rows equally, then sum samples/time")
    ap.add_argument("--workers", default="0,1,2,4",
                    help="comma-separated worker counts (0 = the "
                         "synchronous row)")
    ap.add_argument("--pipelines", default="sync,shm",
                    help="which transports to measure for workers>0 "
                         "(sync ignores the worker count); add 'pool' "
                         "for the retired Pool path A/B")
    ap.add_argument("--wire", default="uint8", choices=("uint8", "f32"),
                    help="image wire format for every row")
    ap.add_argument("--max-people", type=int, default=8,
                    help="joint padding for the device-GT payload")
    ap.add_argument("--train-rate", type=float, default=0.0,
                    help="chip train consumption in imgs/s; 0 reads "
                         "TRAIN_BENCH.json (audited b8 step rate)")
    ap.add_argument("--out", default="INPUT_PIPELINE.json")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import jax

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.data import (CocoPoseDataset, ShmRingInput,
                                              batches, build_fixture)
    from improved_body_parts_tpu.parallel import make_mesh

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    train_rate = args.train_rate
    if not train_rate:
        try:
            with open(os.path.join(repo, "TRAIN_BENCH.json")) as f:
                audit = json.load(f)["batches"]
            # the largest audited train batch (b8: 71.75 imgs/s on-chip)
            train_rate = max(
                (float(v["imgs_per_sec"]) for v in audit.values()))
        except Exception:  # artifact absent — fall back to the known figure
            train_rate = 71.75

    cfg = get_config(args.config)
    mesh = make_mesh()
    size = cfg.skeleton.height
    worker_counts = [int(x) for x in args.workers.split(",")]
    pipelines = [p.strip() for p in args.pipelines.split(",")]

    with tempfile.TemporaryDirectory(prefix="feed_rate_") as work:
        corpus = os.path.join(work, "corpus.h5")
        n_rec = build_fixture(corpus, num_images=args.records,
                              people_per_image=2,
                              img_size=(size * 3 // 4, size),
                              image_size=size, seed=0, drawn=True)
        ds = CocoPoseDataset(corpus, cfg, augment=True)
        print(f"corpus: {n_rec} records at {size}px; chip rate target "
              f"{train_rate:.1f} imgs/s; wire={args.wire}", flush=True)

        # Build every row's batch source up front; persistent rings spawn
        # ONCE here, outside any timed window (idle workers block on the
        # task queue and cost no CPU while other rows measure).
        def _sync_iter(raw_gt):
            return lambda epoch: batches(ds, args.batch, epoch,
                                         raw_gt=raw_gt, wire=args.wire)

        def _pool_iter(raw_gt, w):
            return lambda epoch: batches(ds, args.batch, epoch,
                                         num_workers=w, raw_gt=raw_gt,
                                         pipeline="pool", wire=args.wire)

        specs, rings = [], []
        for mode, raw_gt in (("host_gt", 0), ("device_gt", args.max_people)):
            for w in worker_counts:
                if w <= 0:
                    if "sync" in pipelines:
                        specs.append((mode, "sync", 0, _sync_iter(raw_gt),
                                      True))
                    continue
                if "shm" in pipelines:
                    # stream() pipelines across epoch boundaries (the
                    # steady state a long training corpus sees — the tiny
                    # benchmark corpus would otherwise spend a large
                    # fraction of each ~2-second "epoch" draining the tail)
                    ring = ShmRingInput(ds, args.batch, w, raw_gt=raw_gt,
                                        wire=args.wire)
                    rings.append(ring)
                    specs.append((mode, "shm", w,
                                  lambda epoch, r=ring: r.stream(), True))
                if "pool" in pipelines:
                    # whole epochs only (see measure's abandonable note)
                    specs.append((mode, "pool", w, _pool_iter(raw_gt, w),
                                  False))

        acc = {i: [0, 0.0] for i in range(len(specs))}
        try:
            per_pass = max(args.min_seconds / max(args.repeats, 1), 2.0)
            for rep in range(max(args.repeats, 1)):
                for i, (mode, pipeline, w, make_iter,
                        abandonable) in enumerate(specs):
                    _, n, dt = measure(make_iter, args.batch, mesh, per_pass,
                                       abandonable=abandonable)
                    acc[i][0] += n
                    acc[i][1] += dt
                    time.sleep(0.5)  # let abandoned in-flight work settle
                print(f"round {rep + 1}/{args.repeats} done", flush=True)
        finally:
            for ring in rings:
                ring.close()

        rows = []
        for i, (mode, pipeline, w, _, _a) in enumerate(specs):
            n, dt = acc[i]
            rate = n / dt if dt else 0.0
            rows.append({"mode": mode, "pipeline": pipeline, "workers": w,
                         "samples_per_sec": round(rate, 2),
                         "samples": n, "seconds": round(dt, 2)})
            print(f"{mode} {pipeline} workers={w}: {rate:.2f} samples/s "
                  f"({n} in {dt:.1f}s)", flush=True)

        # capacity projection from the measured steady-state rates: the
        # shm row at <= host core count gives the per-worker rate a real
        # TPU host (many cores) scales linearly; sync is the 1-process
        # baseline
        host_cores = os.cpu_count() or 1
        projection = {}
        for mode in ("host_gt", "device_gt"):
            mrows = [r for r in rows if r["mode"] == mode]
            if not mrows:
                continue
            sync_rate = max((r["samples_per_sec"] for r in mrows
                             if r["pipeline"] == "sync"), default=None)
            in_core = [r for r in mrows
                       if r["pipeline"] == "shm"
                       and 0 < r["workers"] <= host_cores]
            per_worker = max((r["samples_per_sec"] / r["workers"]
                              for r in in_core), default=None)
            if per_worker is None:
                continue
            projection[mode] = {
                "sync_rate": sync_rate,
                "shm_per_worker_rate": round(per_worker, 2),
                "workers_for_one_chip": math.ceil(train_rate / per_worker),
                "workers_for_v5e8": math.ceil(8 * train_rate / per_worker),
            }

        note = None
        if max(worker_counts) >= host_cores:
            note = (f"host has {host_cores} cores: worker counts >= "
                    f"{host_cores} timeshare them with the consumer, so "
                    "measured rates saturate near the core count; "
                    "per-worker projection scales to real TPU hosts")
        result = {
            "config": args.config, "image_size": size, "batch": args.batch,
            "platform": jax.devices()[0].platform,
            "host_cores": host_cores,
            "host_note": note,
            "wire": args.wire,
            "chip_train_rate_imgs_per_sec": train_rate,
            "protocol": "batch source -> parallel.device_prefetch -> "
                        "block_until_ready sink; drawn fixture corpus; "
                        "augment on; shm rows use the persistent "
                        "data.shm_ring stream() (cross-epoch pipelined; "
                        "spawn excluded from the window)",
            "rows": rows,
            "projection": projection,
        }
        with open(args.out, "w") as f:
            strict_dump(result, f, indent=2)
        print(strict_dumps(result))


if __name__ == "__main__":
    main()
