#!/usr/bin/env python
"""Fold a run's JSONL telemetry stream into a human-readable summary.

Reads the event stream written by ``obs.EventSink`` (tools/train.py
``--telemetry-sink``, tools/serve_bench.py, tools/telemetry_overhead.py)
and reports:

- step time (mean / p50 / p95 / max) and imgs/s over the run;
- the **bottleneck verdict**: the data-wait vs device-compute split
  accumulated inside ``parallel.prefetch`` — *input-bound* means the
  chips starved waiting for batches (fix: more ring workers, see
  TRAINING.md §5b), *compute-bound* means the input pipeline kept up
  and the step itself is the frontier;
- the recompile timeline: every post-warmup XLA compile
  (``obs.CompileWatch``), each one a silent multi-second pipeline stall;
- epoch losses, ``timed`` span records, and serve snapshots when
  present;
- per-worker sink shards (``<events>.pN``, written by serve worker
  processes) auto-discovered next to the primary stream and summarized
  SEPARATELY under ``worker_shards`` — a shard whose ``run_start``
  carries a different ``run_id`` than the primary stream is a stale
  leftover from an earlier run and is skipped loudly;
- the run's telemetry-history stream (``<events-stem>_history.jsonl``
  + ``.pN``, written by ``obs.history.HistoryStore``) auto-discovered
  the same way, with the same loud stale-``run_id`` skip, and pointed
  at ``tools/history_report.py`` for rendering (``--no-shards``
  disables both discoveries).

    python tools/telemetry_report.py checkpoints/events.jsonl
    python tools/telemetry_report.py events.jsonl --json report.json
"""
import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    read_events,
    strict_dump,
)

# above this fraction of attributed wall time spent waiting on data the
# run is input-bound; below half of it, compute-bound; between, mixed.
# The threshold lives in obs.registry so tools/trace_report.py's verdict
# over the same split can never drift from this one.
from improved_body_parts_tpu.obs.registry import INPUT_BOUND_FRAC  # noqa: E402


def _pct(xs, q):
    """Exact percentile of a full sample list (a PercentileMeter at
    >=len capacity evicts nothing, so its estimate is exact — one
    quantile implementation shared with the /metrics endpoint)."""
    from improved_body_parts_tpu.utils.meters import PercentileMeter

    m = PercentileMeter(capacity=max(len(xs), 1))
    for v in xs:
        m.update(v)
    return m.percentile(q)


def discover_shards(path):
    """Per-worker sink shards ``<path>.pN`` next to a primary stream
    (worker processes write their own shard so streams never
    interleave).  Globbed rather than probed consecutively from
    ``.p1`` — a crashed worker can leave a numbering hole that must
    not hide the surviving workers' shards."""
    shards = []
    for p in glob.glob(glob.escape(path) + ".p*"):
        suffix = p[len(path) + 2:]
        if suffix.isdigit():
            shards.append((int(suffix), p))
    return [p for _, p in sorted(shards)]


def summarize_shard(path, primary_run_id):
    """Small per-shard summary.  Shards are summarized SEPARATELY,
    never concatenated into the primary stream: a worker's monotonic
    ``t`` axis starts at ITS sink open, not the parent's, so merged
    timings would be nonsense.  Returns ``None`` — after a loud stderr
    note — when the shard's ``run_start`` carries a ``run_id`` other
    than the primary stream's: a stale shard from an earlier run
    sitting next to a fresh primary must not be reported as this run."""
    events = read_events(path)
    header = next((e for e in reversed(events)
                   if e.get("event") == "run_start"), {})
    if header.get("run_id") != primary_run_id:
        print(f"{path}: shard run_id {header.get('run_id')!r} does not "
              f"match the primary stream's {primary_run_id!r}; skipping "
              "stale shard", file=sys.stderr)
        return None
    stop = next((e for e in reversed(events)
                 if e.get("event") == "worker_stop"), None)
    return {
        "path": os.path.basename(path),
        "worker": header.get("worker"),
        "pid": header.get("pid"),
        "role": header.get("role"),
        "events": len(events),
        "served": (stop or {}).get("served"),
        "clean_stop": stop is not None,
    }


def summarize_history(events_path, primary_run_id):
    """Small summary of the telemetry-history stream a
    ``obs.history.HistoryStore`` persisted next to this run's events
    (``<events-stem>_history.jsonl`` + ``.pN`` rotation shards).
    Returns ``None`` when there is no stream, or — after a loud stderr
    note — when its header carries a ``run_id`` other than the primary
    stream's: a stale history from an earlier run sitting next to a
    fresh events file must not be reported as this run.  (A header
    without a run_id is kept: stores wired outside ``RunTelemetry``
    legitimately don't stamp one.)"""
    from improved_body_parts_tpu.obs.history import (
        discover_history_shards, history_path_for)

    hist_path = history_path_for(events_path)
    shards = discover_history_shards(hist_path)
    if not shards:
        return None
    header = next((e for e in read_events(shards[0])
                   if e.get("event") == "history_start"), {})
    hist_run = header.get("run_id")
    if (hist_run is not None and primary_run_id is not None
            and hist_run != primary_run_id):
        print(f"{hist_path}: history run_id {hist_run!r} does not match "
              f"the primary stream's {primary_run_id!r}; skipping stale "
              "history shards", file=sys.stderr)
        return None
    ticks = series = gaps = 0
    last_t = None
    for p in shards:
        for e in read_events(p):
            ev = e.get("event")
            if ev == "history_sample":
                ticks += 1
                last_t = e.get("t", last_t)
            elif ev == "history_gap":
                gaps += 1
    # every shard re-declares its series; count the last shard's
    series = sum(1 for e in read_events(shards[-1])
                 if e.get("event") == "history_series")
    return {
        "path": os.path.basename(hist_path),
        "shards": len(shards),
        "run_id": hist_run,
        "cadence_s": header.get("cadence_s"),
        "ticks": ticks,
        "series": series,
        "gaps": gaps,
        "last_t": last_t,
    }


def summarize(events):
    """Machine-readable summary dict of one parsed event stream.

    The sink appends, so a re-run over the same ``auto`` path (resume /
    retry) stacks runs in one file.  Two cases:

    - plain runs: the summary covers the LAST run — everything from the
      final ``run_start`` header on — and records how many earlier runs
      were skipped (the historical behavior);
    - **elastic runs** (``tools/train.py --supervised``): every
      ``run_start`` carrying the same ``run_id`` as the last one is a
      SEGMENT of one logical run (a preemption/crash/restart boundary,
      not a new run).  Those segments are stitched back together — step
      stats, attribution and epochs aggregate across all of them — and
      a per-segment table (how the previous segment ended, what epoch
      the restore landed on, the resume milestone eval) is added.
    """
    from improved_body_parts_tpu.obs import SCHEMA_VERSION

    starts = [i for i, e in enumerate(events)
              if e.get("event") == "run_start"]
    # split into (header, slice) runs; synthesize one headerless run for
    # legacy streams with no run_start at all
    bounds = starts + [len(events)]
    runs = ([(events[starts[i]], events[starts[i]:bounds[i + 1]])
             for i in range(len(starts))]
            if starts else [({}, events)])
    run_id = runs[-1][0].get("run_id")
    if run_id:
        group = [(h, ev) for h, ev in runs if h.get("run_id") == run_id]
    else:
        group = [runs[-1]]
    previous_runs = len(runs) - len(group)
    header = group[-1][0]
    events = [e for _, ev in group for e in ev]
    schema = max((h.get("schema", 0) for h, _ in group), default=0)
    if schema > SCHEMA_VERSION:
        raise SystemExit(
            f"event stream schema {schema} is newer than this tool's "
            f"{SCHEMA_VERSION}; refusing to misread it — update the repo")

    segments = None
    if run_id:
        segments = []
        for h, ev in group:
            seg_start = next((e for e in ev
                              if e.get("event") == "segment_start"), None)
            seg_end = next((e for e in reversed(ev)
                            if e.get("event") == "segment_end"), None)
            resume = next((e for e in ev
                           if e.get("event") == "resume"), None)
            epochs_in = [e.get("epoch") for e in ev
                         if e.get("event") == "epoch"]
            segments.append({
                "segment": h.get("segment"),
                "time_unix": h.get("time_unix"),
                "previous_end": (seg_start or {}).get("previous_end"),
                "backoff_s": (seg_start or {}).get("backoff_s"),
                "resumed_from": (resume or {}).get("epoch"),
                "resume_eval_loss": next(
                    (e.get("loss") for e in ev
                     if e.get("event") == "resume_eval"), None),
                "windows": sum(1 for e in ev
                               if e.get("event") == "train_step"),
                "epochs": len(epochs_in),
                "epoch_range": ([epochs_in[0], epochs_in[-1]]
                                if epochs_in else None),
                "end": ((seg_end or {}).get("status")
                        or "died (no segment_end)"),
            })

    steps = [e for e in events if e.get("event") == "train_step"]
    epochs = [e for e in events if e.get("event") == "epoch"]
    recompiles = [e for e in events if e.get("event") == "recompile"]
    warm = next((e for e in events
                 if e.get("event") == "warmup_complete"), None)
    timed = [e for e in events if e.get("event") == "timed"]
    serve = [e for e in events if e.get("event", "").startswith("serve")]

    step_s = [e["step_s"] for e in steps if "step_s" in e]
    imgs_s = [e["imgs_per_sec"] for e in steps if "imgs_per_sec" in e]
    wait = sum(e.get("data_wait_s", 0.0) for e in steps)
    hold = sum(e.get("compute_s", 0.0) for e in steps)
    attributed = wait + hold
    wait_frac = wait / attributed if attributed else 0.0

    if not attributed:
        verdict = "unknown (no attributed step records)"
    elif wait_frac >= INPUT_BOUND_FRAC:
        verdict = "input-bound"
    elif wait_frac >= INPUT_BOUND_FRAC / 2:
        verdict = "mixed (input pressure)"
    else:
        verdict = "compute-bound"

    out = {
        "run": {k: header.get(k) for k in
                ("schema", "time_unix", "pid", "tool", "config")
                if k in header or k == "schema"},
        "previous_runs_in_file": previous_runs,
        "run_id": run_id,
        "segments": segments,
        "windows": len(steps),
        "step_seconds": {
            "mean": sum(step_s) / len(step_s) if step_s else 0.0,
            "p50": _pct(step_s, 50), "p95": _pct(step_s, 95),
            "max": max(step_s) if step_s else 0.0,
        },
        "imgs_per_sec": {
            "mean": sum(imgs_s) / len(imgs_s) if imgs_s else 0.0,
            "last": imgs_s[-1] if imgs_s else 0.0,
        },
        "attribution": {
            "data_wait_s": round(wait, 6),
            "compute_s": round(hold, 6),
            "data_wait_frac": round(wait_frac, 4),
            "compute_frac": round(1.0 - wait_frac, 4) if attributed else 0.0,
        },
        "verdict": verdict,
        "warmup_complete_t": warm.get("t") if warm else None,
        "recompiles_post_warmup": len(recompiles),
        "recompile_timeline": [
            {"t": e.get("t"), "duration_s": e.get("duration_s"),
             "source": e.get("source")} for e in recompiles],
        "epochs": [{"epoch": e.get("epoch"),
                    "train_loss": e.get("train_loss"),
                    **({"val_loss": e["val_loss"]} if "val_loss" in e
                       else {})} for e in epochs],
        "timed_spans": len(timed),
        "serve_events": len(serve),
    }
    return out


def render(summary):
    """Human-readable report text."""
    s = summary
    lines = []
    run = s["run"]
    lines.append("== telemetry report ==")
    lines.append(f"run: tool={run.get('tool', '?')} "
                 f"config={run.get('config', '?')} pid={run.get('pid')}")
    if s.get("previous_runs_in_file"):
        lines.append(f"(file holds {s['previous_runs_in_file']} earlier "
                     "run(s); reporting the last)")
    segs = s.get("segments")
    if segs and len(segs) > 1:
        lines.append(f"elastic run {s.get('run_id')}: {len(segs)} "
                     "segments stitched (stats below aggregate all of "
                     "them)")
        lines.append("  seg  prev-end       resumed  windows  epochs"
                     "   resume-eval  end")
        for g in segs:
            er = g.get("epoch_range")
            er_txt = f"{er[0]}-{er[1]}" if er else "-"
            rev = g.get("resume_eval_loss")
            rev_txt = f"{rev:.4f}" if rev is not None else "-"
            rf = g.get("resumed_from")
            lines.append(
                f"  {g.get('segment', '?'):>3}  "
                f"{str(g.get('previous_end', '?')):<13}  "
                f"{str(rf) if rf is not None else '-':>7}  "
                f"{g.get('windows', 0):>7}  {er_txt:>6}  "
                f"{rev_txt:>11}  {g.get('end', '?')}")
    st = s["step_seconds"]
    lines.append(
        f"steps: {s['windows']} windows | step "
        f"{st['mean'] * 1e3:.1f} ms mean / {st['p50'] * 1e3:.1f} p50 / "
        f"{st['p95'] * 1e3:.1f} p95 / {st['max'] * 1e3:.1f} max | "
        f"{s['imgs_per_sec']['mean']:.1f} imgs/s mean")
    a = s["attribution"]
    lines.append(
        f"attribution: data-wait {a['data_wait_s']:.2f} s "
        f"({a['data_wait_frac'] * 100:.1f}%) vs compute "
        f"{a['compute_s']:.2f} s ({a['compute_frac'] * 100:.1f}%)")
    lines.append(f"verdict: {s['verdict']}")
    if s["verdict"] == "input-bound":
        lines.append("  -> the device starved on input; add ring workers "
                     "(tools/feed_rate.py sizes them, TRAINING.md 5b)")
    elif s["verdict"].startswith("compute"):
        lines.append("  -> input kept up; the step itself is the "
                     "frontier (tools/train_bench.py / perf_audit.py)")
    n_rc = s["recompiles_post_warmup"]
    if s["warmup_complete_t"] is None:
        lines.append("recompiles: warmup never marked (short/aborted run)")
    elif n_rc == 0:
        lines.append("recompiles after warmup: 0 (steady state held)")
    else:
        lines.append(f"recompiles after warmup: {n_rc} — each one is a "
                     "silent pipeline stall:")
        for e in s["recompile_timeline"][:20]:
            lines.append(f"  t={e['t']:.2f}s  {e['duration_s']:.3f}s "
                         f"({e['source']})")
        if n_rc > 20:
            lines.append(f"  ... {n_rc - 20} more")
    if s["epochs"]:
        last = s["epochs"][-1]
        lines.append(f"epochs: {len(s['epochs'])} | last train_loss "
                     f"{last.get('train_loss')}"
                     + (f" val_loss {last['val_loss']}"
                        if "val_loss" in last else ""))
    if s.get("worker_shards"):
        lines.append(f"worker sink shards: {len(s['worker_shards'])}")
        for g in s["worker_shards"]:
            served = g.get("served")
            lines.append(
                f"  worker {g.get('worker')} (pid {g.get('pid')}): "
                f"{g['events']} events, served "
                f"{served if served is not None else '?'}, "
                + ("clean stop" if g["clean_stop"]
                   else "no worker_stop (crashed?)"))
    h = s.get("history")
    if h:
        lines.append(
            f"telemetry history: {h['path']} — {h['shards']} shard(s), "
            f"{h['ticks']} ticks @ {h['cadence_s']}s, {h['series']} "
            f"series, {h['gaps']} gap(s)"
            " (tools/history_report.py renders it)")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events", help="JSONL event stream "
                                   "(obs.EventSink output)")
    ap.add_argument("--json", default=None,
                    help="also write the machine-readable summary here")
    ap.add_argument("--no-shards", action="store_true",
                    help="skip auto-discovery of <events>.pN worker "
                         "sink shards and the <events-stem>_history"
                         ".jsonl telemetry-history stream")
    args = ap.parse_args()

    events = read_events(args.events)
    if not events:
        raise SystemExit(f"no events parsed from {args.events}")
    summary = summarize(events)
    shard_paths = [] if args.no_shards else discover_shards(args.events)
    if shard_paths:
        shards = [summarize_shard(p, summary.get("run_id"))
                  for p in shard_paths]
        summary["worker_shards"] = [s for s in shards if s is not None]
    if not args.no_shards:
        hist = summarize_history(args.events, summary.get("run_id"))
        if hist is not None:
            summary["history"] = hist
    print(render(summary))
    if args.json:
        with open(args.json, "w") as f:
            strict_dump(summary, f, indent=2)
        print(f"\nwrote {args.json}")


if __name__ == "__main__":
    main()
