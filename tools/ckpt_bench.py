#!/usr/bin/env python
"""Epoch-boundary checkpoint stall: sync vs async, on a real fit().

The last serial host-side stall in the training path was the per-epoch
checkpoint: the loop blocked on the whole Orbax write (snapshot +
serialize + fsync) before the next epoch could start.  The async
``CheckpointManager`` blocks only on the device→host snapshot drain and
commits in background, overlapping validation and the next epoch's
steps.  This benchmark measures exactly that number — **train-loop
blocked seconds per save** (``CheckpointManager.blocked_seconds``) — on
a real multi-epoch ``fit`` of the canonical-shape tiny config (model +
SGD momentum + batch_stats + the SWA shadow, the full flagship state
CONTENT at test width), in interleaved ABBA rounds per the
serve_bench/feed_rate protocol so host-load drift hits both arms
equally.  The verdict is the median over per-round stall ratios.

Also verifies the two paths are INTERCHANGEABLE (an async-saved and a
sync-saved checkpoint of the same state restore bit-identical leaves)
and, from an instrumented run's span trace, that the ``serialize`` /
``commit`` spans actually overlap subsequent ``step_window`` / eval
spans — the timeline proof that the write left the loop's critical
path.

Registered as the ``"ckpt"`` key in bench.py (``IBP_BENCH_CKPT=0``
skips; budget-aware).

    python tools/ckpt_bench.py                    # 3 rounds x 3 epochs
    python tools/ckpt_bench.py --rounds 5 --epochs 4 --steps 6
"""
import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

STALL_REDUCTION_TARGET = 5.0


def _spans(events, names):
    """(start_us, end_us, name) for every complete X span named in
    ``names`` from a trace_event list."""
    out = []
    for e in events:
        if e.get("ph") == "X" and e.get("name") in names:
            out.append((e["ts"], e["ts"] + e.get("dur", 0.0), e["name"]))
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="tiny",
                    help="model/config under test (tiny = the flagship "
                         "IMHN shape family at test width; the state "
                         "carries params + momentum + batch_stats + the "
                         "SWA shadow either way)")
    ap.add_argument("--rounds", type=int, default=3,
                    help="interleaved sync/async rounds (ABBA order)")
    ap.add_argument("--epochs", type=int, default=3,
                    help="fit epochs per arm per round — every epoch "
                         "boundary is one measured save")
    ap.add_argument("--steps", type=int, default=12,
                    help="train steps per epoch — enough wall time for "
                         "the background write to hide behind (real "
                         "epochs are minutes; epochs shorter than the "
                         "write re-expose it as wait time at the next "
                         "save, which the stall number honestly counts)")
    ap.add_argument("--eval-steps", type=int, default=4)
    ap.add_argument("--print-freq", type=int, default=2)
    ap.add_argument("--out", default="CKPT_BENCH.json")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when the stall-reduction target "
                         "or bit-identity fails")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import dataclasses

    import jax
    import numpy as np

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs import Registry, RunTelemetry
    from improved_body_parts_tpu.parallel import make_mesh, replicated
    from improved_body_parts_tpu.train import (
        CheckpointManager, create_train_state, make_eval_step,
        make_optimizer, make_train_step, read_commit_meta,
        restore_checkpoint, save_checkpoint, start_swa,
        step_decay_schedule)
    from improved_body_parts_tpu.train.loop import fit

    cfg = get_config(args.config)
    cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, print_freq=args.print_freq))
    model = build_model(cfg)
    mesh = make_mesh()
    n_dev = int(mesh.devices.size)
    batch = max(cfg.train.batch_size_per_device, 1) * n_dev
    size = cfg.skeleton.height
    grid = size // cfg.skeleton.stride
    rng = np.random.default_rng(0)

    imgs = rng.uniform(0, 1, (batch, size, size, 3)).astype(np.float32)
    labels = rng.uniform(
        0, 1, (batch, grid, grid, cfg.skeleton.num_layers)
    ).astype(np.float32)
    mask = np.ones((batch, grid, grid, 1), np.float32)

    def make_batches(epoch):
        def gen():
            for _ in range(args.steps):
                yield (imgs, mask, labels)
        return gen()

    def make_eval_batches(epoch):
        def gen():
            for _ in range(args.eval_steps):
                yield (imgs, mask, labels)
        return gen()

    opt = make_optimizer(cfg, step_decay_schedule(cfg.train,
                                                  steps_per_epoch=100))
    state0 = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                                imgs[:1])
    # the canonical checkpoint CONTENT: params + SGD momentum +
    # batch_stats + the SWA shadow (what the flagship run serializes)
    state0 = start_swa(state0)
    # master host copy: each arm re-places it fresh — the fit arms run a
    # DONATED step, which consumes the device buffers
    master = jax.tree.map(lambda x: np.asarray(x).copy(), state0)
    payload_bytes = int(sum(
        getattr(leaf, "nbytes", 0) for leaf in jax.tree.leaves(master)))

    train_step = make_train_step(model, cfg, opt)  # donate=True (default)
    eval_step = make_eval_step(model, cfg)
    quiet = lambda s: None  # noqa: E731 — stdout stays one JSON line

    work = tempfile.mkdtemp(prefix="ckpt_bench_")

    def run_arm(async_save, tag, telemetry=None):
        """One fit; returns the manager's per-save blocked seconds."""
        d = os.path.join(work, tag)
        shutil.rmtree(d, ignore_errors=True)
        manager = CheckpointManager(d, async_save=async_save)
        state = jax.device_put(master, replicated(mesh))
        fit(state, train_step, cfg, make_batches, args.epochs, mesh=mesh,
            eval_step=eval_step, make_eval_batches=make_eval_batches,
            checkpoint_dir=d, log_fn=quiet, telemetry=telemetry,
            checkpoint_manager=manager)
        manager.close()
        return manager.blocked_seconds, d

    # untimed warmup: compiles the donated train step + eval step and
    # pays orbax's first-save setup for both arms
    run_arm(False, "warm_sync")
    run_arm(True, "warm_async")

    sync_rounds, async_rounds = [], []
    for i in range(max(1, args.rounds)):
        # ABBA: alternate which arm goes first so a host-load ramp
        # cannot systematically penalize one arm (serve_bench protocol)
        order = [(False, sync_rounds), (True, async_rounds)]
        if i % 2:
            order.reverse()
        for async_save, sink in order:
            blocked, _ = run_arm(async_save,
                                 f"r{i}_{'async' if async_save else 'sync'}")
            sink.append(blocked)

    sync_flat = [v for r in sync_rounds for v in r]
    async_flat = [v for r in async_rounds for v in r]
    per_round_ratio = [statistics.mean(s) / max(statistics.mean(a), 1e-9)
                       for s, a in zip(sync_rounds, async_rounds)]
    reduction = statistics.median(per_round_ratio)

    # ---- interchangeability: one state, both paths, identical leaves
    sync_path = save_checkpoint(os.path.join(work, "ident_sync"), state0,
                                0, 1.0, 1.0)
    with CheckpointManager(os.path.join(work, "ident_async")) as m:
        async_path = m.save(state0, 0, 1.0, 1.0)
    a, b = restore_checkpoint(sync_path), restore_checkpoint(async_path)
    bit_identical = (
        jax.tree.structure(a) == jax.tree.structure(b)
        and all(np.asarray(x).dtype == np.asarray(y).dtype
                and np.array_equal(np.asarray(x), np.asarray(y))
                for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))))

    # ---- instrumented run: the trace must SHOW the write off the
    # critical path (serialize/commit overlapping later step/eval spans)
    ev_path = os.path.join(work, "events.jsonl")
    trace_path = os.path.join(work, "trace.json")
    tele = RunTelemetry(ev_path, registry=Registry(),
                        run_meta={"tool": "ckpt_bench"},
                        trace_path=trace_path, watch_compiles=False)
    try:
        run_arm(True, "instrumented", telemetry=tele)
        trace_events = tele.trace.events()
    finally:
        tele.close()
    writes = _spans(trace_events, {"serialize", "commit"})
    targets = _spans(trace_events, {"step_window", "eval_epoch",
                                    "data_wait", "compute"})
    overlaps = sum(
        1 for w0, w1, _ in writes
        for t0, t1, _ in targets
        if t0 > w0 and t0 < w1)  # a LATER span started inside the write
    snapshots = _spans(trace_events, {"snapshot"})

    report = {
        "config": args.config,
        "protocol": "real multi-epoch fit (donated jitted step, eval "
                     "overlap) per arm; interleaved ABBA rounds; stall = "
                     "CheckpointManager.blocked_seconds per save; "
                     "verdict = median per-round sync/async ratio",
        "rounds": args.rounds,
        "epochs_per_arm": args.epochs,
        "steps_per_epoch": args.steps,
        "payload_bytes": payload_bytes,
        "saves_per_arm": len(sync_rounds[0]) if sync_rounds else 0,
        "sync_stall_ms_mean": round(statistics.mean(sync_flat) * 1e3, 3),
        "sync_stall_ms_median": round(
            statistics.median(sync_flat) * 1e3, 3),
        "async_stall_ms_mean": round(statistics.mean(async_flat) * 1e3, 3),
        "async_stall_ms_median": round(
            statistics.median(async_flat) * 1e3, 3),
        "per_round_stall_reduction": [round(r, 2) for r in per_round_ratio],
        "stall_reduction": round(reduction, 2),
        "stall_reduction_target": STALL_REDUCTION_TARGET,
        "meets_target": bool(reduction >= STALL_REDUCTION_TARGET),
        "bit_identical_restore": bool(bit_identical),
        "write_spans": len(writes),
        "snapshot_spans": len(snapshots),
        "write_overlapping_later_spans": overlaps,
        "write_overlaps_step_or_eval": bool(overlaps > 0),
        "trace": trace_path,
        "telemetry_events": ev_path,
        "host_note": f"cpu_count={os.cpu_count()}, "
                     f"backend={jax.default_backend()}",
        "commit_meta_sample": read_commit_meta(async_path),
    }
    with open(args.out, "w") as f:
        strict_dump(report, f, indent=2)
    print(strict_dumps(report))
    if args.strict and not (report["meets_target"]
                            and report["bit_identical_restore"]):
        sys.exit(1)


if __name__ == "__main__":
    main()
