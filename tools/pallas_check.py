#!/usr/bin/env python
"""On-device validation of the Pallas focal-L2 kernel vs the XLA path.

VERDICT r1 next #8: the kernel is parity-tested in interpreter mode on CPU
(tests/test_pallas_focal.py); this tool runs it under the REAL lowering
(Mosaic on TPU) on whatever platform is active, checks fwd+grad parity
against ops.losses.focal_l2 semantics, and times both. Run it the moment a
chip is available:

    python tools/pallas_check.py            # active platform (TPU if up)
    JAX_PLATFORMS=cpu python tools/pallas_check.py --interpret

Flip ``use_pallas_loss`` default only if the kernel wins on hardware.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description="pallas focal kernel check")
    ap.add_argument("--stacks", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--hw", type=int, default=128)
    ap.add_argument("--channels", type=int, default=50)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--interpret", action="store_true",
                    help="Pallas interpreter mode (CPU debugging)")
    args = ap.parse_args()
    if args.iters < 1:
        ap.error("--iters must be >= 1")

    import jax

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax.numpy as jnp
    import numpy as np

    from improved_body_parts_tpu.ops.losses import focal_l2
    from improved_body_parts_tpu.ops.pallas_focal import focal_l2_pallas

    try:
        platform = devices_with_timeout(600)[0].platform
    except (RuntimeError, TimeoutError) as e:
        raise SystemExit(str(e))
    print(f"platform={platform} interpret={args.interpret}")

    S, N, H, C = args.stacks, args.batch, args.hw, args.channels
    rng = np.random.default_rng(0)
    pred = jnp.asarray(rng.uniform(-0.2, 1.2, (S, N, H, H, C)), jnp.float32)
    gt = jnp.asarray(rng.uniform(0, 1, (N, H, H, C)) *
                     (rng.uniform(0, 1, (N, H, H, C)) > 0.7), jnp.float32)
    mask = jnp.asarray(rng.uniform(0, 1, (N, H, H, 1)) > 0.1, jnp.float32)
    chan = np.ones((C,), np.float32)
    chan[-2] = 0.1   # person-mask channel ×multi_task_weight
    chan[30:48] = 3  # keypoint channels ×keypoint_task_weight
    chan = jnp.asarray(chan)

    # XLA reference: the ACTUAL training loss (ops.losses.focal_l2) with the
    # channel modulation folded into the mask — validating against the real
    # thing, not a frozen copy of its math
    def xla_focal(pred, gt, mask, chan):
        return focal_l2(pred, gt[None], (mask * chan)[None])

    pallas_fn = jax.jit(
        lambda p, g, m, c: focal_l2_pallas(p, g, m, c, args.interpret))
    xla_fn = jax.jit(xla_focal)

    out_p = jax.block_until_ready(pallas_fn(pred, gt, mask, chan))
    out_x = jax.block_until_ready(xla_fn(pred, gt, mask, chan))
    fwd_err = float(jnp.abs(out_p - out_x).max() / jnp.abs(out_x).max())
    print(f"forward rel err: {fwd_err:.2e}")

    g_p = jax.jit(jax.grad(lambda p: pallas_fn(p, gt, mask, chan).sum()))
    g_x = jax.jit(jax.grad(lambda p: xla_fn(p, gt, mask, chan).sum()))
    gp = jax.block_until_ready(g_p(pred))
    gx = jax.block_until_ready(g_x(pred))
    grad_err = float(jnp.abs(gp - gx).max() / (jnp.abs(gx).max() + 1e-12))
    print(f"grad rel err:    {grad_err:.2e}")

    def bench(fn, *a):
        jax.block_until_ready(fn(*a))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.iters * 1e3

    t_p = bench(pallas_fn, pred, gt, mask, chan)
    t_x = bench(xla_fn, pred, gt, mask, chan)
    t_gp = bench(g_p, pred)
    t_gx = bench(g_x, pred)
    print(f"forward: pallas {t_p:7.3f} ms   xla {t_x:7.3f} ms   "
          f"({t_x / t_p:.2f}x)")
    print(f"grad:    pallas {t_gp:7.3f} ms   xla {t_gx:7.3f} ms   "
          f"({t_gx / t_gp:.2f}x)")
    verdict = "PALLAS WINS" if (t_p < t_x and t_gp < t_gx) else "XLA wins"
    # fp32 sums over ~100k terms differ by reduction order between the
    # per-tile accumulation and XLA's tree reduction; 1e-4 relative is the
    # numerical-noise band, not a semantic mismatch
    ok = fwd_err < 1e-4 and grad_err < 1e-4
    print(f"parity {'OK' if ok else 'FAIL'}; {verdict} "
          f"(flip use_pallas_loss only if pallas wins on TPU)")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
