#!/usr/bin/env python
"""On-device validation of the Pallas focal-L2 kernel vs the XLA path.

VERDICT r1 next #8: the kernel is parity-tested in interpreter mode on CPU
(tests/test_pallas_focal.py); this tool runs it under the REAL lowering
(Mosaic on TPU) on whatever platform is active, checks fwd+grad parity
against ops.losses.focal_l2 semantics, and times both. Run it the moment a
chip is available:

    python tools/pallas_check.py            # active platform (TPU if up)
    JAX_PLATFORMS=cpu python tools/pallas_check.py --interpret

Flip ``use_pallas_loss`` default only if the kernel wins on hardware.

``--assembly`` checks the OTHER sketched kernel instead: the decode
assembly's inner candidate walk (ops/pallas_assembly.py, the Mosaic
variant of the fused decode program's bounded while_loop) — parity
against the host reference walk plus timing.  Same rule: wire it into
``ops.assembly.greedy_assemble`` only if it wins on hardware.

``--peaks`` / ``--limbs`` check the ISSUE 20 decode kernels
(ops/pallas_peaks.py): the per-channel top-K peak extractor and the
dense (L,K,K,S) limb-sample gather.  Parity there is EXACT (bitwise
against ops.peaks — the payloads feed the deterministic assembly), and
the flags compose: ``--peaks --limbs`` runs both.  Flip
``InferenceParams.use_pallas_decode`` only on a hardware win.

``--json PATH`` writes every kernel row run this invocation as a
strict-JSON artifact (the committed ``PALLAS_CHECK.json``), so a TPU
session can re-bless the A/B with one command:

    python tools/pallas_check.py --peaks --limbs --json PALLAS_CHECK.json
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _write_json(path, platform, interpret, rows):
    import jax

    from improved_body_parts_tpu.obs.events import strict_dump

    doc = {"platform": platform, "interpret": bool(interpret),
           "jax_version": jax.__version__,
           "parity_ok": all(r["parity_ok"] for r in rows),
           "kernels": rows}
    with open(path, "w") as f:
        strict_dump(doc, f, indent=2)
        f.write("\n")
    print(f"wrote {path} ({len(rows)} kernel row(s))")


def main():
    ap = argparse.ArgumentParser(description="pallas focal kernel check")
    ap.add_argument("--stacks", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--hw", type=int, default=128)
    ap.add_argument("--channels", type=int, default=50)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--interpret", action="store_true",
                    help="Pallas interpreter mode (CPU debugging)")
    ap.add_argument("--assembly", action="store_true",
                    help="check the decode-assembly candidate-walk "
                         "kernel (ops/pallas_assembly.py) instead of "
                         "the focal loss")
    ap.add_argument("--peaks", action="store_true",
                    help="check the top-K peak-extraction kernel "
                         "(ops/pallas_peaks.py, exact parity)")
    ap.add_argument("--limbs", action="store_true",
                    help="check the limb pair-stats gather kernel "
                         "(ops/pallas_peaks.py, exact parity)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the kernel rows as a strict-JSON "
                         "artifact (PALLAS_CHECK.json)")
    args = ap.parse_args()
    if args.iters < 1:
        ap.error("--iters must be >= 1")

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    from improved_body_parts_tpu.ops.pallas_focal import parity_benchmark

    try:
        platform = devices_with_timeout(600)[0].platform
    except (RuntimeError, TimeoutError) as e:
        raise SystemExit(str(e))
    print(f"platform={platform} interpret={args.interpret}")

    if args.peaks or args.limbs:
        from improved_body_parts_tpu.ops.pallas_peaks import (
            limbs_parity_benchmark, peaks_parity_benchmark)

        rows = []
        if args.peaks:
            rows.append(peaks_parity_benchmark(
                h=args.hw, w=args.hw, iters=args.iters,
                interpret=args.interpret))
        if args.limbs:
            rows.append(limbs_parity_benchmark(
                h=args.hw, w=args.hw, iters=args.iters,
                interpret=args.interpret))
        for r in rows:
            verdict = "PALLAS WINS" if r["pallas_wins"] else "XLA wins"
            print(f"{r['kernel']:12s} pallas {r['pallas_ms']:7.3f} ms   "
                  f"xla {r['xla_ms']:7.3f} ms   "
                  f"exact parity {'OK' if r['parity_ok'] else 'FAIL'} "
                  f"({r['trials']} randomized trials); {verdict}")
        if args.json:
            _write_json(args.json, platform, args.interpret, rows)
        print("flip InferenceParams.use_pallas_decode only if the "
              "Mosaic lowerings win on TPU")
        sys.exit(0 if all(r["parity_ok"] for r in rows) else 1)

    if args.assembly:
        from improved_body_parts_tpu.ops.pallas_assembly import (
            walk_parity_benchmark,
        )

        r = walk_parity_benchmark(iters=args.iters,
                                  interpret=args.interpret)
        print(f"candidate walk: pallas {r['pallas_ms']:7.3f} ms   "
              f"host reference {r['host_ms']:7.3f} ms "
              f"({r['trials']} randomized parity trials)")
        print(f"parity {'OK' if r['parity_ok'] else 'FAIL'}; wire into "
              "greedy_assemble only if the Mosaic lowering wins on TPU")
        sys.exit(0 if r["parity_ok"] else 1)

    r = parity_benchmark(stacks=args.stacks, batch=args.batch, hw=args.hw,
                         channels=args.channels, iters=args.iters,
                         interpret=args.interpret)
    print(f"forward rel err: {r['rel_err']:.2e}")
    print(f"grad rel err:    {r['grad_rel_err']:.2e}")
    print(f"forward: pallas {r['pallas_ms']:7.3f} ms   "
          f"xla {r['xla_ms']:7.3f} ms")
    print(f"grad:    pallas {r['pallas_grad_ms']:7.3f} ms   "
          f"xla {r['xla_grad_ms']:7.3f} ms")
    verdict = "PALLAS WINS" if r["pallas_wins"] else "XLA wins"
    print(f"parity {'OK' if r['parity_ok'] else 'FAIL'}; {verdict} "
          f"(flip use_pallas_loss only if pallas wins on TPU)")
    sys.exit(0 if r["parity_ok"] else 1)


if __name__ == "__main__":
    main()
