#!/usr/bin/env python
"""On-device validation of the Pallas focal-L2 kernel vs the XLA path.

VERDICT r1 next #8: the kernel is parity-tested in interpreter mode on CPU
(tests/test_pallas_focal.py); this tool runs it under the REAL lowering
(Mosaic on TPU) on whatever platform is active, checks fwd+grad parity
against ops.losses.focal_l2 semantics, and times both. Run it the moment a
chip is available:

    python tools/pallas_check.py            # active platform (TPU if up)
    JAX_PLATFORMS=cpu python tools/pallas_check.py --interpret

Flip ``use_pallas_loss`` default only if the kernel wins on hardware.

``--assembly`` checks the OTHER sketched kernel instead: the decode
assembly's inner candidate walk (ops/pallas_assembly.py, the Mosaic
variant of the fused decode program's bounded while_loop) — parity
against the host reference walk plus timing.  Same rule: wire it into
``ops.assembly.greedy_assemble`` only if it wins on hardware.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description="pallas focal kernel check")
    ap.add_argument("--stacks", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--hw", type=int, default=128)
    ap.add_argument("--channels", type=int, default=50)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--interpret", action="store_true",
                    help="Pallas interpreter mode (CPU debugging)")
    ap.add_argument("--assembly", action="store_true",
                    help="check the decode-assembly candidate-walk "
                         "kernel (ops/pallas_assembly.py) instead of "
                         "the focal loss")
    args = ap.parse_args()
    if args.iters < 1:
        ap.error("--iters must be >= 1")

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    from improved_body_parts_tpu.ops.pallas_focal import parity_benchmark

    try:
        platform = devices_with_timeout(600)[0].platform
    except (RuntimeError, TimeoutError) as e:
        raise SystemExit(str(e))
    print(f"platform={platform} interpret={args.interpret}")

    if args.assembly:
        from improved_body_parts_tpu.ops.pallas_assembly import (
            walk_parity_benchmark,
        )

        r = walk_parity_benchmark(iters=args.iters,
                                  interpret=args.interpret)
        print(f"candidate walk: pallas {r['pallas_ms']:7.3f} ms   "
              f"host reference {r['host_ms']:7.3f} ms "
              f"({r['trials']} randomized parity trials)")
        print(f"parity {'OK' if r['parity_ok'] else 'FAIL'}; wire into "
              "greedy_assemble only if the Mosaic lowering wins on TPU")
        sys.exit(0 if r["parity_ok"] else 1)

    r = parity_benchmark(stacks=args.stacks, batch=args.batch, hw=args.hw,
                         channels=args.channels, iters=args.iters,
                         interpret=args.interpret)
    print(f"forward rel err: {r['rel_err']:.2e}")
    print(f"grad rel err:    {r['grad_rel_err']:.2e}")
    print(f"forward: pallas {r['pallas_ms']:7.3f} ms   "
          f"xla {r['xla_ms']:7.3f} ms")
    print(f"grad:    pallas {r['pallas_grad_ms']:7.3f} ms   "
          f"xla {r['xla_grad_ms']:7.3f} ms")
    verdict = "PALLAS WINS" if r["pallas_wins"] else "XLA wins"
    print(f"parity {'OK' if r['parity_ok'] else 'FAIL'}; {verdict} "
          f"(flip use_pallas_loss only if pallas wins on TPU)")
    sys.exit(0 if r["parity_ok"] else 1)


if __name__ == "__main__":
    main()
