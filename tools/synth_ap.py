#!/usr/bin/env python
"""Learn→AP integration benchmark on the drawn-person synthetic fixture.

The image contains no COCO data, checkpoint, or pycocotools, so real AP
parity (reference: evaluate.py:585-622, README.md:76-79) cannot be
measured here.  This tool provides the strongest in-image substitute: it
demonstrates the ENTIRE loop — corpus build → augmented training via the
real train CLI → checkpoint → multi-path inference → decode → OKS AP on a
HELD-OUT val set — actually learns, using rendered stick figures
(data/fixture.py ``drawn=True``) whose colored limbs/joints are genuinely
learnable from pixels (the plain noise fixture only supports overfit
tests).

    python tools/synth_ap.py --out SYNTH_AP.json

Writes one JSON artifact with the AP of the trained model on held-out
images, plus an untrained-baseline AP for contrast.

``--dtype-matrix`` additionally re-evaluates the TRAINED checkpoint
under the serve weight-storage dtypes (``evaluate.py --params-dtype``
bf16 and int8 — the same ``apply_serve_dtype`` chain the export gate
fingerprints), and gates the on-chip campaign's quantization tolerance:
|AP(int8) − AP(bf16)| must stay within 1 synthetic-AP point
(SYNTH_AP_DTYPE.json).
"""
import argparse
import json
import os
import re
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


def run_cli(args, env_extra=None, timeout=7200, cwd=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra or {})
    proc = subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, env=env, timeout=timeout, cwd=cwd)
    if proc.returncode != 0:
        raise RuntimeError(
            f"{args[0]} failed rc={proc.returncode}\n"
            f"stdout: {proc.stdout[-2000:]}\nstderr: {proc.stderr[-2000:]}")
    return proc.stdout


def parse_ap(stdout: str) -> float:
    # floats in any notation Python prints: 0.42, 9.9e-05, nan
    m = re.search(r"^AP: ([0-9.eE+-]+|nan)$", stdout, re.MULTILINE)
    if not m:
        raise RuntimeError(f"no AP line in output tail: {stdout[-800:]}")
    return float(m.group(1))


def _save_fresh_checkpoint(config_name: str, directory: str) -> str:
    """An untrained-parameter checkpoint for the baseline evaluation.

    Runs in a SUBPROCESS pinned to CPU: initializing a backend in the
    orchestrator itself would, on an exclusively-claimed accelerator, hold
    the claim and deadlock the next eval subprocess.
    """
    run_cli([os.path.abspath(__file__), "--make-fresh-checkpoint",
             config_name, directory],
            env_extra={"JAX_PLATFORMS": "cpu"})
    from improved_body_parts_tpu.train.checkpoint import latest_checkpoint
    path = latest_checkpoint(directory)
    assert path, f"fresh checkpoint missing under {directory}"
    return path


def _save_fresh_checkpoint_impl(config_name: str, directory: str) -> str:
    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import jax
    import jax.numpy as jnp
    import optax

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.train import create_train_state
    from improved_body_parts_tpu.train.checkpoint import save_checkpoint
    from improved_body_parts_tpu.models import build_model

    cfg = get_config(config_name)
    model = build_model(cfg)
    imgs = jnp.zeros((1, cfg.skeleton.height, cfg.skeleton.width, 3),
                     jnp.float32)
    opt = optax.sgd(1e-3, momentum=0.9)
    state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0), imgs)
    return save_checkpoint(directory, state, 0, float("inf"), float("inf"))


def main():
    ap = argparse.ArgumentParser(
        description="synthetic learn->AP integration benchmark")
    ap.add_argument("--config", default="synth")
    ap.add_argument("--train-images", type=int, default=96)
    ap.add_argument("--val-images", type=int, default=24)
    ap.add_argument("--people", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=0,
                    help="0 = the config's own epoch budget (synth: 60, "
                         "the SYNTH_AP.json headline protocol)")
    ap.add_argument("--canvas", type=int, nargs=2, default=(192, 256),
                    metavar=("H", "W"))
    ap.add_argument("--workdir", default=None,
                    help="default: a fresh temp dir")
    ap.add_argument("--out", default="SYNTH_AP.json")
    ap.add_argument("--decode-path", default="compact",
                    choices=["full", "fast", "compact"])
    ap.add_argument("--lr", type=float, default=0.0,
                    help="override the config learning rate (passed to "
                         "the train CLI; use e.g. 5e-4 for corpora much "
                         "larger than ~100 images — see configs.py synth)")
    ap.add_argument("--workers", type=int, default=0,
                    help="corpus worker processes for the train CLI; 0 "
                         "(synchronous) is fastest on few-core hosts — "
                         "each spawned worker re-imports the jax stack")
    ap.add_argument("--crowd", action="store_true",
                    help="render unannotated people + crowd regions into "
                         "train AND val (miss-masked in training, "
                         "iscrowd-ignored in eval) — the end-to-end "
                         "exercise of the reference's mask_miss semantics")
    ap.add_argument("--no-miss-mask", action="store_true",
                    help="ablation for --crowd: identical corpus but with "
                         "mask_miss forced to all-ones, so training "
                         "penalizes detections of the unannotated extras")
    ap.add_argument("--device-gt", type=int, default=0,
                    help="train with on-device GT synthesis (--device-gt "
                         "N = max_people padding passed to the train CLI)")
    ap.add_argument("--train-timeout", type=int, default=0,
                    help="seconds before the train subprocess is killed; "
                         "0 = scale with the epoch count (600 s/epoch + "
                         "1 h slack, floor 2 h) — the old fixed 7200 s "
                         "silently killed production-shape runs "
                         "mid-training (synth_deep measures ~320 s/epoch "
                         "on a contended 1-core host)")
    ap.add_argument("--hard", action="store_true",
                    help="harder corpus tier: wider scale range and "
                         "per-person rotations up to +-60 deg (beyond "
                         "the +-40 training augmentation) in train AND "
                         "val -- the benchmark arm where rotation TTA "
                         "should pay (reference: evaluate.py:89-90)")
    ap.add_argument("--seed", type=int, default=0,
                    help="replication seed: varies the train corpus AND "
                         "the train CLI's init/data seed; the val set "
                         "stays fixed (--val-seed) so seeds are compared "
                         "on identical held-out data")
    ap.add_argument("--val-seed", type=int, default=12345,
                    help="val-set seed (use 777 with --val-images 64 for "
                         "the big-val protocol of SYNTH_AP_DEEP_BIGVAL)")
    ap.add_argument("--dtype-matrix", action="store_true",
                    help="re-evaluate the trained checkpoint under the "
                         "serve storage dtypes (bf16, int8 weight-only "
                         "quantization) and gate |AP(int8) - AP(bf16)| "
                         "<= 0.01 (1 synthetic-AP point)")
    ap.add_argument("--keep-workdir", action="store_true")
    ap.add_argument("--train-platform", default="",
                    help="JAX_PLATFORMS for the train subprocess (e.g. "
                         "'axon' to train on the TPU). Default: inherit "
                         "the environment (cpu if unset). Only ONE "
                         "subprocess should target an exclusively-claimed "
                         "accelerator at a time; the fresh-checkpoint "
                         "helper is always pinned to cpu for this reason.")
    ap.add_argument("--eval-platform", default="",
                    help="JAX_PLATFORMS for the evaluate subprocesses; "
                         "set 'cpu' when training on an exclusive-claim "
                         "accelerator to avoid a second claim bind (the "
                         "decode/OKS protocol is platform-agnostic)")
    args = ap.parse_args()

    # the whole benchmark is a CPU protocol check unless the caller
    # explicitly targets an accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.data import build_fixture, build_val_set

    # absolute: the eval subprocesses run with cwd=work, so relative
    # paths handed to them would double-resolve
    work = os.path.abspath(args.workdir or tempfile.mkdtemp(prefix="synth_ap_"))
    os.makedirs(work, exist_ok=True)
    cfg = get_config(args.config)
    epochs = args.epochs or cfg.train.epochs
    net_size = cfg.skeleton.height
    canvas = tuple(args.canvas)
    # scale val images so the average person lands at the same size the
    # transformer normalizes to during training (target_dist of net_size)
    boxsize = net_size

    corpus = os.path.join(work, "train_drawn.h5")
    n_rec = build_fixture(corpus, num_images=args.train_images,
                          people_per_image=args.people, img_size=canvas,
                          image_size=net_size, seed=args.seed, drawn=True,
                          crowd=args.crowd, hard=args.hard,
                          mask_extras=not args.no_miss_mask)
    val_dir = os.path.join(work, "val")
    anno = os.path.join(work, "person_keypoints_synth.json")
    n_val = build_val_set(val_dir, anno, num_images=args.val_images,
                          people_per_image=args.people, img_size=canvas,
                          image_size=net_size, seed=args.val_seed, drawn=True,
                          crowd=args.crowd, hard=args.hard)
    print(f"corpus: {n_rec} records; val: {n_val} persons "
          f"({args.val_images} images)", flush=True)

    ckpt_dir = os.path.join(work, "ckpt")
    print(f"training {args.config} for {epochs} epochs...", flush=True)
    train_args = [os.path.join(REPO, "tools", "train.py"),
                  "--config", args.config, "--epochs", str(epochs),
                  "--train-h5", corpus, "--checkpoint-dir", ckpt_dir,
                  "--workers", str(args.workers), "--print-freq", "20",
                  "--seed", str(args.seed)]
    if args.lr:
        train_args += ["--lr", str(args.lr)]
    if args.device_gt:
        train_args += ["--device-gt", str(args.device_gt)]
    train_env = ({"JAX_PLATFORMS": args.train_platform}
                 if args.train_platform else None)
    run_cli(train_args, env_extra=train_env,
            timeout=args.train_timeout or max(7200, 600 * epochs + 3600))
    # per-epoch losses live in the reference-format append-only epoch log
    with open(os.path.join(ckpt_dir, "log")) as f:
        losses = re.findall(r"train_loss: ([0-9.eE+-]+)", f.read())
    print(f"loss first->last: {losses[0] if losses else '?'} -> "
          f"{losses[-1] if losses else '?'}", flush=True)

    from improved_body_parts_tpu.train.checkpoint import latest_checkpoint
    latest = latest_checkpoint(ckpt_dir)
    assert latest, f"no checkpoint under {ckpt_dir}"

    decode_flag = {"full": [], "fast": ["--fast"],
                   "compact": ["--compact"]}[args.decode_path]
    # --dump-name is a NAME fragment (the dump lands under the eval
    # subprocess CWD as results/person_keypoints_<name>.json), so run the
    # evals with cwd=work and distinct names to keep artifacts in the
    # workdir and the two evals apart
    eval_args = [os.path.join(REPO, "tools", "evaluate.py"),
                 "--config", args.config, "--anno", anno,
                 "--images", val_dir, "--oks-proxy",
                 "--boxsize", str(boxsize)] + decode_flag
    eval_env = ({"JAX_PLATFORMS": args.eval_platform}
                if args.eval_platform else None)
    print("evaluating trained checkpoint...", flush=True)
    ap_trained = parse_ap(run_cli(
        eval_args + ["--checkpoint", latest, "--dump-name", "synth_trained"],
        cwd=work, env_extra=eval_env))

    dtype_matrix = {}
    if args.dtype_matrix:
        # the serve storage-dtype matrix over the SAME checkpoint, val
        # set and decode path — only apply_serve_dtype's weight storage
        # varies, so the AP deltas are pure quantization effect
        for dtype in ("bf16", "int8"):
            print(f"evaluating trained checkpoint @ {dtype}...",
                  flush=True)
            dtype_matrix[dtype] = parse_ap(run_cli(
                eval_args + ["--checkpoint", latest,
                             "--params-dtype", dtype,
                             "--dump-name", f"synth_trained_{dtype}"],
                cwd=work, env_extra=eval_env))

    # contrast: an untrained (fresh-init) model through the same protocol
    # — shows the AP is learned, not an artifact of the decoder
    fresh_dir = os.path.join(work, "ckpt_fresh")
    fresh = _save_fresh_checkpoint(args.config, fresh_dir)
    print("evaluating untrained baseline...", flush=True)
    ap_fresh = parse_ap(run_cli(
        eval_args + ["--checkpoint", fresh, "--dump-name", "synth_fresh"],
        cwd=work, env_extra=eval_env))

    result = {
        "config": args.config,
        "train_images": args.train_images, "train_records": n_rec,
        "val_images": args.val_images, "val_persons": n_val,
        "epochs": epochs, "people_per_image": args.people,
        "lr": args.lr or cfg.train.learning_rate_per_device,
        "canvas": list(canvas), "decode_path": args.decode_path,
        "crowd": args.crowd, "miss_mask": not args.no_miss_mask,
        "device_gt": args.device_gt,
        "seed": args.seed, "val_seed": args.val_seed, "hard": args.hard,
        "train_platform": args.train_platform
        or os.environ.get("JAX_PLATFORMS", "cpu"),
        "eval_platform": args.eval_platform
        or os.environ.get("JAX_PLATFORMS", "cpu"),
        "train_loss_first": float(losses[0]) if losses else None,
        "train_loss_last": float(losses[-1]) if losses else None,
        "train_loss_curve": [float(v) for v in losses],
        "ap_trained": ap_trained, "ap_untrained": ap_fresh,
        "protocol": "drawn-person fixture; held-out val (different seed); "
                    "OKS-proxy evaluator (APCHECK.md); real train/evaluate "
                    "CLIs as subprocesses",
    }
    if args.dtype_matrix:
        delta = abs(dtype_matrix["int8"] - dtype_matrix["bf16"])
        result["ap_trained_bf16"] = dtype_matrix["bf16"]
        result["ap_trained_int8"] = dtype_matrix["int8"]
        result["int8_vs_bf16_ap_delta"] = round(delta, 6)
        result["int8_ap_tolerance"] = 0.01  # 1 synthetic-AP point
        result["int8_within_tolerance"] = bool(delta <= 0.01)
    with open(args.out, "w") as f:
        strict_dump(result, f, indent=2)
    print(strict_dumps(result))
    if args.dtype_matrix and not result["int8_within_tolerance"]:
        sys.exit(1)
    if not args.keep_workdir and args.workdir is None:
        import shutil
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) == 4 and sys.argv[1] == "--make-fresh-checkpoint":
        # internal subcommand used by _save_fresh_checkpoint
        _save_fresh_checkpoint_impl(sys.argv[2], sys.argv[3])
    else:
        main()
