#!/usr/bin/env python
"""Fault-injection harness for elastic training (train.supervisor).

Runs a REAL multi-epoch ``tools/train.py --supervised`` fit on a
synthetic corpus and kills it repeatedly — SIGKILL at deterministic
in-process points (mid-step window, while the async checkpoint write is
in flight, on the checkpoint writer thread between the Orbax write and
the commit marker, mid-eval), external SIGTERM mid-epoch (the clean
preemption drain), and a hard kill of a shm-ring worker (which the
supervised ring must REBUILD, not abort on).  After every death it
relaunches the same command line — exactly what a spot-capacity
scheduler does — until the run's ledger says the epoch target was
reached.  Asserted end to end:

- every resume lands on the last checkpoint that was COMMITTED before
  the kill (read post-mortem from the directory, compared against the
  next segment's ``resume`` event);
- no processes leak: every descendant of a killed child (ring workers
  included — their orphan watchdog must fire) is gone within a grace
  window, and the final segment's ``segment_end`` records no surviving
  checkpoint-writer thread;
- the final state matches an uninterrupted control run of the same
  seed/epochs: bit-wise where the host's XLA numerics reproduce, else
  the final train/val losses track within ``--loss-tol`` (the DATA
  stream is bit-identical by the shm-ring contract, but an A/A control
  experiment on the 2-core cpu-shares bench host showed XLA:CPU step
  numerics themselves drift run-to-run — two byte-identical command
  lines landed 0.8% apart — so bit-equality is reported but cannot be
  the gate there).

Writes a CHAOS.json artifact; registered as bench.py's ``"chaos"`` key
(``IBP_BENCH_CHAOS=0`` skips).  The tier-1 smoke
(tests/test_supervisor.py) runs ``--kills 2 --no-control``; the full
randomized sweep is the ``slow``-marked test / the committed artifact.

    python tools/chaos_train.py                     # 8 randomized kills
    python tools/chaos_train.py --kills 3 --epochs 3 --no-control
"""
import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- process utils
def _proc_table():
    """pid -> ppid for every live process (Linux /proc)."""
    table = {}
    for name in os.listdir("/proc"):
        if not name.isdigit():
            continue
        try:
            with open(f"/proc/{name}/stat") as f:
                fields = f.read().split()
            table[int(name)] = int(fields[3])
        except (OSError, IndexError, ValueError):
            continue
    return table


def _descendants(pid):
    """All live descendant pids of ``pid`` (ring workers, trackers)."""
    table = _proc_table()
    children = {}
    for p, pp in table.items():
        children.setdefault(pp, []).append(p)
    out, frontier = [], [pid]
    while frontier:
        nxt = []
        for p in frontier:
            for c in children.get(p, []):
                out.append(c)
                nxt.append(c)
        frontier = nxt
    return out


def _cmdline(pid):
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode(errors="replace")
    except OSError:
        return ""


def _ring_worker_pids(child_pid):
    """Spawned multiprocessing children of the train process that are
    ring workers (not the resource tracker)."""
    return [p for p in _descendants(child_pid)
            if "spawn_main" in _cmdline(p)
            and "resource_tracker" not in _cmdline(p)]


def _wait_gone(pids, timeout_s=20.0):
    """Wait for pids to exit; returns the survivors (leaks)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        alive = [p for p in pids if os.path.exists(f"/proc/{p}")]
        if not alive:
            return []
        time.sleep(0.25)
    return [p for p in pids if os.path.exists(f"/proc/{p}")]


# ------------------------------------------------------------------- events
def _read_events(path):
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
    except OSError:
        pass
    return out


def _wait_for_event(path, pred, child, timeout_s=240.0, start=0):
    """Poll the live JSONL stream until an event AFTER index ``start``
    satisfies ``pred`` (or the child exits / the timeout passes).
    ``start`` matters: the sink appends across segments, so scanning
    from 0 would satisfy this segment's wait with a previous segment's
    events.  Returns the event or None."""
    deadline = time.monotonic() + timeout_s
    seen = start
    while time.monotonic() < deadline:
        events = _read_events(path)
        for e in events[seen:]:
            if pred(e):
                return e
        seen = max(seen, len(events))
        if child.poll() is not None:
            # one final read: the event may have landed with the exit
            for e in _read_events(path)[seen:]:
                if pred(e):
                    return e
            return None
        time.sleep(0.1)
    return None


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--epochs", type=int, default=6,
                    help="TOTAL epoch target of the supervised run "
                         "(enough runway that the default 8 injections "
                         "all fire before the target lands)")
    ap.add_argument("--records", type=int, default=6,
                    help="fixture corpus size (steps/epoch = records / "
                         "batch)")
    ap.add_argument("--val-records", type=int, default=2)
    ap.add_argument("--workers", type=int, default=1,
                    help="shm-ring workers in every child (>=1 so the "
                         "ring-worker-kill injection has a target)")
    ap.add_argument("--kills", type=int, default=8,
                    help="fault injections before the run is allowed to "
                         "finish")
    ap.add_argument("--seed", type=int, default=0,
                    help="harness RNG seed (injection plan) AND the "
                         "training seed of both arms")
    ap.add_argument("--print-freq", type=int, default=1)
    ap.add_argument("--no-control", action="store_true",
                    help="skip the uninterrupted control run and the "
                         "final bit-match (the fast smoke mode)")
    ap.add_argument("--segment-timeout", type=int, default=420,
                    help="hard per-child wall bound")
    ap.add_argument("--loss-tol", type=float, default=0.02,
                    help="relative final-loss tolerance vs the control "
                         "run when the host's XLA numerics are not "
                         "bit-reproducible")
    ap.add_argument("--out", default="CHAOS.json")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when any assertion fails")
    args = ap.parse_args()

    rng = random.Random(args.seed)
    work = tempfile.mkdtemp(prefix="chaos_train_")

    from improved_body_parts_tpu.data import build_fixture

    train_h5 = os.path.join(work, "train.h5")
    val_h5 = os.path.join(work, "val.h5")
    build_fixture(train_h5, num_images=args.records, people_per_image=1,
                  seed=args.seed + 3)
    build_fixture(val_h5, num_images=args.val_records, people_per_image=1,
                  seed=args.seed + 7)

    def child_env(extra=None):
        env = dict(os.environ)
        env.pop("IBP_CHAOS_KILL", None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            # children share one persistent compile cache: segment 2+
            # (and the control run) skip the XLA compile entirely, which
            # is what keeps an 8-kill sweep inside the bench budget
            "JAX_COMPILATION_CACHE_DIR": os.path.join(work, "jax_cache"),
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0.5",
            "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
        })
        env.update(extra or {})
        return env

    def argv(ckpt_dir, supervised=True):
        out = [sys.executable, os.path.join(REPO, "tools", "train.py"),
               "--config", args.config, "--epochs", str(args.epochs),
               "--train-h5", train_h5, "--val-h5", val_h5,
               "--checkpoint-dir", ckpt_dir, "--workers",
               str(args.workers), "--print-freq", str(args.print_freq),
               "--seed", str(args.seed), "--telemetry-sink", "auto"]
        if supervised:
            out += ["--supervised", "--backoff-base", "0.1",
                    "--backoff-max", "2"]
        return out

    # ---- control run: same seed/epochs, no faults ----------------------
    control = {"skipped": True}
    control_dir = os.path.join(work, "control")
    if not args.no_control:
        t0 = time.monotonic()
        proc = subprocess.run(argv(control_dir, supervised=False),
                              env=child_env(), capture_output=True,
                              text=True, timeout=args.segment_timeout * 3)
        control = {"returncode": proc.returncode,
                   "wall_s": round(time.monotonic() - t0, 1)}
        if proc.returncode != 0:
            control["stderr_tail"] = proc.stderr[-1500:]

    # ---- chaos run: inject, die, relaunch, until completed -------------
    chaos_dir = os.path.join(work, "chaos")
    from improved_body_parts_tpu.train.checkpoint import (
        latest_checkpoint, read_commit_meta)

    def committed_epoch():
        path = latest_checkpoint(chaos_dir)
        if path is None:
            return -1
        meta = read_commit_meta(path)
        return meta["epoch"] if meta else -1

    # deterministic in-process SIGKILL points + external signals, in a
    # seed-randomized order.  Hit COUNTS are chosen at segment-launch
    # time, spread across the epochs the segment still has to run (the
    # points recur once per window / save / eval): early kills restart
    # from scratch, later kills land AFTER commits so the sweep
    # exercises real resume-from-epoch-N — not only fresh restarts —
    # while staying inside the remaining budget so every armed
    # injection actually fires before the segment could complete.
    steps_per_epoch = max(args.records // 2, 1)  # tiny config: batch 2
    kinds = ["window", "post_save", "mid_ckpt_write", "mid_eval",
             "sigterm", "ring_worker"]
    plan = [kinds[i % len(kinds)] if args.kills >= len(kinds)
            else rng.choice(kinds) for i in range(args.kills)]
    rng.shuffle(plan)

    def pick_hit(kind, committed):
        """Randomized n-th-hit trigger for an in-process kill point,
        bounded by the FIRST HALF of what the segment will reach (it
        resumes at ``committed + 1`` and runs ``epochs`` total): inside
        the budget so every armed kill fires before the segment could
        complete, early enough that a sweep of ``--kills`` injections
        all land before the epoch target does."""
        remaining = max(args.epochs - (committed + 1), 1)
        half = max(remaining // 2, 1)
        if kind == "window":
            return rng.randint(1, steps_per_epoch * half)
        # post_save / mid_ckpt_write / mid_eval each fire once per epoch
        return rng.randint(1, half)

    events_path = os.path.join(chaos_dir, "events.jsonl")
    segments = []
    injected = 0
    completed = False
    leaked_total = []
    resume_mismatches = []
    max_segments = args.kills + 6  # every injection + recovery headroom

    for seg_idx in range(max_segments):
        kind = plan[injected] if injected < len(plan) else "none"
        committed_before = committed_epoch()
        hit = (pick_hit(kind, committed_before)
               if kind in ("window", "post_save", "mid_ckpt_write",
                           "mid_eval") else (1 if kind != "none" else 0))
        env_extra = {}
        if hit and kind not in ("sigterm", "ring_worker"):
            env_extra["IBP_CHAOS_KILL"] = f"{kind}:{hit}"
        events_before = len(_read_events(events_path))
        t0 = time.monotonic()
        child = subprocess.Popen(
            argv(chaos_dir), env=child_env(env_extra),
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)
        try:
            # snapshot the process tree as soon as THIS segment's
            # training is underway — BEFORE any injected death — so the
            # leak check covers the ring workers (best-effort: a very
            # early in-process kill can beat the snapshot; the
            # end-of-sweep orphan scan is the backstop).  start= matters:
            # earlier segments' train_step events must not satisfy it.
            _wait_for_event(
                events_path, lambda e: e.get("event") == "train_step",
                child, timeout_s=args.segment_timeout,
                start=events_before)
            descendants = (_descendants(child.pid)
                           if child.poll() is None else [])
            if kind == "sigterm" and child.poll() is None:
                child.send_signal(signal.SIGTERM)  # the clean drain
            elif kind == "ring_worker" and child.poll() is None:
                # kill EVERY ring worker mid-fit (train + eval rings —
                # killing only a random one could pick the eval ring,
                # whose death goes unnoticed until the next eval): the
                # supervised train ring must REBUILD mid-epoch
                # (observable as a ring_rebuild event) — then SIGKILL
                # the segment so the sweep continues with the remaining
                # injections
                for pid in _ring_worker_pids(child.pid):
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except OSError:
                        pass
                _wait_for_event(
                    events_path,
                    lambda e: e.get("event") == "ring_rebuild",
                    child, timeout_s=120, start=events_before)
                if child.poll() is None:
                    os.kill(child.pid, signal.SIGKILL)
            child.wait(timeout=args.segment_timeout)
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait(timeout=30)
        wall = time.monotonic() - t0
        stderr = child.stderr.read() if child.stderr else ""
        committed_after = committed_epoch()
        leaked = _wait_gone(descendants)
        leaked_total += leaked

        # did THIS segment resume from the epoch committed before it?
        seg_events = _read_events(events_path)[events_before:]
        resume = next((e for e in seg_events
                       if e.get("event") == "resume"), None)
        resume_ok = True
        if resume is not None and committed_before >= 0:
            resume_ok = resume.get("epoch") == committed_before
        elif resume is not None and committed_before < 0:
            resume_ok = resume.get("found") is False
        if not resume_ok:
            resume_mismatches.append(
                {"segment": seg_idx, "expected": committed_before,
                 "resume_event": resume})
        seg_end = next((e for e in reversed(seg_events)
                        if e.get("event") == "segment_end"), None)
        record = {
            "segment": seg_idx,
            "injection": ({"kind": kind, "hit": hit}
                          if kind != "none" else None),
            "returncode": child.returncode,
            "wall_s": round(wall, 1),
            "committed_before": committed_before,
            "committed_after": committed_after,
            "resumed_from": (resume or {}).get("epoch"),
            "resume_ok": resume_ok,
            "leaked_pids": leaked,
            "ring_rebuilds": sum(1 for e in seg_events
                                 if e.get("event") == "ring_rebuild"),
            "end_status": (seg_end or {}).get("status"),
            "live_threads_at_end": (seg_end or {}).get("live_threads"),
        }
        if child.returncode not in (0, -signal.SIGKILL) and stderr:
            record["stderr_tail"] = stderr[-1200:]
        segments.append(record)
        finished = bool(seg_end and seg_end.get("status") == "completed")
        if kind != "none" and not (finished and child.returncode == 0):
            # only count an injection that actually took the segment
            # down (a run completing under an armed-but-unfired trigger
            # is a miss, not a kill)
            injected += 1
        if finished:
            completed = True
            break
        if child.returncode == 0 and kind == "none" and not seg_end:
            # clean exit without a ledger close — should not happen
            break

    # ---- verdicts ------------------------------------------------------
    final_ok = None
    bit_identical = None
    loss_match = None
    loss_rel_diff = None
    if completed and not args.no_control \
            and control.get("returncode") == 0:
        import numpy as np

        from improved_body_parts_tpu.train.checkpoint import (
            latest_checkpoint, read_commit_meta, restore_checkpoint)

        a = latest_checkpoint(control_dir)
        b = latest_checkpoint(chaos_dir)
        pa, pb = restore_checkpoint(a), restore_checkpoint(b)
        import jax

        bit_identical = (
            jax.tree.structure(pa) == jax.tree.structure(pb)
            and all(np.asarray(x).dtype == np.asarray(y).dtype
                    and np.array_equal(np.asarray(x), np.asarray(y))
                    for x, y in zip(jax.tree.leaves(pa),
                                    jax.tree.leaves(pb))))
        ma, mb = read_commit_meta(a) or {}, read_commit_meta(b) or {}
        diffs = []
        # metric_value only compares when both markers keyed the SAME
        # metric: a chaos arm killed between its final save and eval
        # carries metric=train_loss while the control's was amended to
        # val_loss — cross-metric numbers are not comparable
        keys = ["train_loss"]
        if ma.get("metric") == mb.get("metric"):
            keys.append("metric_value")
        for key in keys:
            ca, cb = ma.get(key), mb.get(key)
            if isinstance(ca, (int, float)) and isinstance(cb, (int, float)):
                diffs.append(abs(ca - cb) / max(abs(ca), 1e-12))
        loss_rel_diff = max(diffs) if diffs else None
        loss_match = (loss_rel_diff is not None
                      and loss_rel_diff <= args.loss_tol)
        # bit-equality is the gold verdict where the host reproduces;
        # the tolerance gate is the fallback for hosts whose XLA:CPU
        # numerics drift run-to-run even A/A (see module docstring)
        final_ok = bool(bit_identical or loss_match)

    # end-of-sweep backstop: any spawn_main worker reparented to init is
    # an orphan this run created (the per-segment snapshot can miss a
    # worker when the injected kill beats the snapshot poll)
    time.sleep(5.0)
    orphans = [p for p, pp in _proc_table().items()
               if pp == 1 and "spawn_main" in _cmdline(p)
               and "resource_tracker" not in _cmdline(p)]
    leaked_total += [p for p in orphans if p not in leaked_total]

    writer_leak = any(
        any("ckpt-writer" in t for t in (s.get("live_threads_at_end")
                                         or []))
        for s in segments)
    report = {
        "protocol": (
            "supervised tools/train.py fit on a synthetic corpus; "
            f"{injected} injections (deterministic SIGKILL points + "
            "external SIGTERM + ring-worker kill) in seed-randomized "
            "order; relaunch-until-completed; resume target checked "
            "against the post-mortem committed epoch; descendants "
            "tracked for leaks; final state compared bit-wise against "
            "an uninterrupted control run"),
        "config": args.config, "epochs": args.epochs,
        "records": args.records, "workers": args.workers,
        "seed": args.seed,
        "injections_planned": len(plan),
        "injections_done": injected,
        "injection_kinds": sorted(set(plan)),
        "segments": segments,
        "segments_total": len(segments),
        "completed": completed,
        "resume_mismatches": resume_mismatches,
        "all_resumes_on_last_committed": not resume_mismatches,
        "leaked_pids_total": len(leaked_total),
        "writer_thread_leaked": writer_leak,
        "control": control,
        "final_bit_identical": bit_identical,
        "final_loss_rel_diff": loss_rel_diff,
        "loss_tol": args.loss_tol,
        "final_loss_match": loss_match,
        "final_matches_control": final_ok,
        "host_note": (
            f"cpu_count={os.cpu_count()}; A/A control experiment on this "
            "host class: two byte-identical unsupervised runs were NOT "
            "bit-identical (XLA:CPU numeric drift), so the loss-tolerance "
            "gate is the operative verdict here"),
        "workdir": work,
    }
    ok = (completed and not resume_mismatches and not leaked_total
          and not writer_leak
          and (final_ok is not False))
    report["ok"] = bool(ok)
    with open(args.out, "w") as f:
        strict_dump(report, f, indent=2)
    print(strict_dumps({k: report[k] for k in (
        "ok", "completed", "injections_done", "segments_total",
        "all_resumes_on_last_committed", "leaked_pids_total",
        "writer_thread_leaked", "final_bit_identical",
        "final_loss_rel_diff", "final_matches_control")}))
    if args.strict and not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
