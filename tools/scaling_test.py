#!/usr/bin/env python
"""Weak-scaling measurement: steps/s at increasing device counts with a
fixed per-device batch (north star: linear data-parallel scaling,
BASELINE.md:25).

On real multi-chip hardware this reports weak-scaling efficiency directly.
On a virtual CPU mesh (``JAX_PLATFORMS=cpu`` with
``--xla_force_host_platform_device_count=N``) the numbers measure
*correct compilation and execution*, not speedup — all virtual devices
timeshare the host's cores, so efficiency trends toward 1/N there; use
tests/test_scaling.py for the cross-mesh equivalence proof instead.

Example:
    python tools/scaling_test.py --config tiny --devices 1 2 4 8 --steps 20
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description="weak-scaling steps/s")
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--devices", type=int, nargs="+", default=[1, 2, 4, 8])
    ap.add_argument("--batch-per-device", type=int, default=1)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=None,
                    help="override H=W (default: the config's input size)")
    args = ap.parse_args()

    import jax

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import jax.numpy as jnp
    import numpy as np

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.parallel import (
        make_mesh, replicated, shard_batch)
    from improved_body_parts_tpu.train import (
        create_train_state, make_optimizer, make_train_step,
        step_decay_schedule)

    cfg = get_config(args.config)
    size = args.image_size or cfg.skeleton.height
    label = size // cfg.skeleton.stride
    model = build_model(cfg)
    rng = np.random.default_rng(0)

    n_avail = len(jax.devices())
    print(f"platform={jax.devices()[0].platform} devices={n_avail}")
    base = None
    for n in args.devices:
        if n > n_avail:
            print(f"n={n}: skipped (only {n_avail} devices)")
            continue
        mesh = make_mesh(data=n, model=1, devices=jax.devices()[:n])
        gb = args.batch_per_device * n
        images = np.asarray(rng.uniform(0, 1, (gb, size, size, 3)),
                            np.float32)
        labels = np.asarray(
            rng.uniform(0, 1, (gb, label, label, cfg.skeleton.num_layers)),
            np.float32)
        mask = np.ones((gb, label, label, 1), np.float32)

        sched = step_decay_schedule(cfg.train, steps_per_epoch=100)
        opt = make_optimizer(cfg, sched)
        state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                                   jnp.zeros((gb, size, size, 3)))
        state = jax.device_put(state, replicated(mesh))
        batch = shard_batch((images, mask, labels), mesh)
        step = make_train_step(model, cfg, opt, donate=False)

        state, loss = step(state, *batch)  # compile + warm
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            state, loss = step(state, *batch)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        sps = args.steps / dt
        ips = sps * gb
        if base is None:
            base = ips / n
        eff = ips / (base * n)
        print(f"n={n}: {sps:6.2f} steps/s  {ips:7.2f} imgs/s  "
              f"weak-scaling eff {eff:5.1%}")


if __name__ == "__main__":
    main()
