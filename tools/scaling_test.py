#!/usr/bin/env python
"""Weak-scaling curve artifact for the GSPMD-partitioned train step.

Drives the REAL partitioned program (``make_train_step(mesh=, rules=)``
— rule-sharded param/optimizer state, batch over 'data', donated) at a
fixed per-device batch across increasing device counts and writes
``SCALING.json``.

Protocol (the ROADMAP standing constraint: single-shot wall-clock on a
shared CPU host is noise — perf claims use interleaved verdict rounds):

- every mesh size n ∈ ``--devices`` is set up and warmed FIRST (one
  compile each, outside every timing window);
- then ``--rounds`` rounds run; each round times ``--steps`` chained
  steps (step i+1 consumes step i's state — donation makes this the
  real training dependence chain) at EVERY n back-to-back, so slow
  host phases hit all mesh sizes alike instead of biasing one;
- the per-n verdict is the MEDIAN over rounds; the curve verdict is
  monotone non-decreasing global imgs/s within ``--tolerance``.

On real multi-chip hardware this reports weak-scaling efficiency
directly.  On a virtual CPU mesh (the committed artifact's host) all
devices timeshare the host's cores, so per-device efficiency trends to
1/n and the honest claim is the one gated here: growing the mesh grows
GLOBAL throughput monotonically — partitioning overhead (collectives,
sharded layouts) does not eat the added devices.  Numerical equivalence
across mesh shapes is pinned separately (tests/test_scaling.py,
tests/test_partition.py); this tool additionally records per-n
first-step loss parity vs n=1 for the artifact.

Example:
    python tools/scaling_test.py --devices 1 2 4 8 --steps 10 \
        --rounds 3 --out SCALING.json
"""
import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description="weak-scaling curve artifact")
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--devices", type=int, nargs="+", default=[1, 2, 4, 8])
    ap.add_argument("--batch-per-device", type=int, default=1)
    ap.add_argument("--steps", type=int, default=10,
                    help="chained steps per (round, n) timing segment")
    ap.add_argument("--rounds", type=int, default=3,
                    help="interleaved verdict rounds (median wins)")
    ap.add_argument("--image-size", type=int, default=None,
                    help="override H=W (default: the config's input size)")
    ap.add_argument("--rules", default="imhn",
                    help="partition ruleset (parallel.partition."
                         "NAMED_RULESETS); 'replicated' reproduces the "
                         "retired dryrun layout as an A/B arm")
    ap.add_argument("--model-axis", type=int, default=1,
                    help="'model' mesh-axis size at the LARGEST n "
                         "(smaller n fall back to 1 when indivisible)")
    ap.add_argument("--min-shard-dim", type=int, default=None,
                    help="per-device shard-extent floor for the rule "
                         "refinement (default: the library's 8 — the "
                         "flagship-width setting; the tiny bench model "
                         "is narrow, so smaller floors shard more of "
                         "it at large n)")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed fractional dip between consecutive "
                         "median imgs/s points before the curve is "
                         "non-monotone")
    ap.add_argument("--out", default=None,
                    help="write the SCALING.json artifact here")
    args = ap.parse_args()

    # the committed artifact runs on a virtual CPU mesh: force the
    # device count BEFORE jax initializes (no-op when enough exist)
    want = max(args.devices)
    flag = f"--xla_force_host_platform_device_count={want}"
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = \
            (os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import jax.numpy as jnp
    import numpy as np

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs.events import strict_dump, strict_dumps
    from improved_body_parts_tpu.parallel import (
        get_ruleset, make_mesh, rules_fingerprint, shard_batch,
        sharding_summary, train_state_shardings)
    from improved_body_parts_tpu.train import (
        create_train_state, make_optimizer, make_train_step,
        step_decay_schedule)

    from improved_body_parts_tpu.parallel.partition import \
        DEFAULT_MIN_SHARD_DIM

    cfg = get_config(args.config)
    size = args.image_size or cfg.skeleton.height
    label = size // cfg.skeleton.stride
    model = build_model(cfg)
    rules = get_ruleset(args.rules)
    min_shard = args.min_shard_dim or DEFAULT_MIN_SHARD_DIM
    rng = np.random.default_rng(0)

    n_avail = len(jax.devices())
    platform = jax.devices()[0].platform
    print(f"platform={platform} devices={n_avail} rules={args.rules}"
          f"#{rules_fingerprint(rules, min_shard_dim=min_shard)}")

    # ---- setup + warm every mesh size OUTSIDE the timing rounds ------
    arms = {}
    for n in args.devices:
        if n > n_avail:
            print(f"n={n}: skipped (only {n_avail} devices)")
            continue
        model_ax = args.model_axis if n % args.model_axis == 0 \
            and n >= args.model_axis else 1
        mesh = make_mesh(data=n // model_ax, model=model_ax,
                         devices=jax.devices()[:n])
        gb = args.batch_per_device * (n // model_ax)
        images = np.asarray(rng.uniform(0, 1, (gb, size, size, 3)),
                            np.float32)
        labels = np.asarray(
            rng.uniform(0, 1, (gb, label, label, cfg.skeleton.num_layers)),
            np.float32)
        mask = np.ones((gb, label, label, 1), np.float32)

        sched = step_decay_schedule(cfg.train, steps_per_epoch=100)
        opt = make_optimizer(cfg, sched)
        shardings = train_state_shardings(model, cfg, opt, mesh, rules,
                                          min_shard_dim=min_shard)
        state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                                   jnp.zeros((gb, size, size, 3)),
                                   shardings=shardings)
        batch = shard_batch((images, mask, labels), mesh)
        # the REAL donated partitioned program — what tools/train.py
        # --partition runs and graftaudit registers; the placed state's
        # OWN sharding tree feeds the jit (one layout source)
        step = make_train_step(model, cfg, opt, mesh=mesh, rules=rules,
                               state_shardings=shardings)
        t0 = time.perf_counter()
        state, loss = step(state, *batch)
        jax.block_until_ready(loss)
        warm_s = time.perf_counter() - t0
        realized = sharding_summary(shardings)
        arms[n] = {"mesh": {"data": n // model_ax, "model": model_ax},
                   "mesh_obj": mesh, "shardings": shardings,
                   "global_batch": gb, "state": state, "batch": batch,
                   "step": step, "first_loss": float(loss),
                   "warm_s": round(warm_s, 2), "sharding": realized}
        print(f"n={n}: warmed in {warm_s:.1f}s, global_batch={gb}, "
              f"state sharding {realized}")

    sizes = sorted(arms)
    assert sizes, "no runnable mesh sizes"

    # ---- partitioned-vs-single-device loss parity --------------------
    # SAME fixture batch, SAME initial state (same PRNGKey), the
    # largest partitioned mesh vs one device: the documented XLA:CPU
    # cross-layout drift bounds the difference (different float
    # reduction orders; tests/test_partition.py pins rel 2e-5 on the
    # update too).  The partitioned side reuses the warmed arm's
    # compiled donated program (same shapes); only the single-device
    # twin compiles extra.
    n_big = sizes[-1]
    big = arms[n_big]
    gbp = big["global_batch"]
    prng = np.random.default_rng(1234)
    p_images = np.asarray(prng.uniform(0, 1, (gbp, size, size, 3)),
                          np.float32)
    p_labels = np.asarray(
        prng.uniform(0, 1, (gbp, label, label, cfg.skeleton.num_layers)),
        np.float32)
    p_mask = np.ones((gbp, label, label, 1), np.float32)
    sched = step_decay_schedule(cfg.train, steps_per_epoch=100)
    opt = make_optimizer(cfg, sched)
    single_step = make_train_step(model, cfg, opt, donate=False)
    s_state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                                 jnp.zeros((gbp, size, size, 3)))
    _, loss_s = single_step(s_state, p_images, p_mask, p_labels)
    p_state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                                 jnp.zeros((gbp, size, size, 3)),
                                 shardings=big["shardings"])
    pb = shard_batch((p_images, p_mask, p_labels), big["mesh_obj"])
    _, loss_p = big["step"](p_state, *pb)
    parity_rel = abs(float(loss_p) - float(loss_s)) \
        / max(abs(float(loss_s)), 1e-12)
    parity = {
        "global_batch": gbp,
        "partitioned_mesh": big["mesh"],
        "single_device_loss": float(loss_s),
        "partitioned_loss": float(loss_p),
        "rel_diff": round(parity_rel, 9),
        "tolerance": 2e-5,
        "ok": bool(parity_rel <= 2e-5),
    }
    print(f"parity: single {float(loss_s):.6f} vs partitioned "
          f"{float(loss_p):.6f} (rel {parity_rel:.2e}) "
          f"ok={parity['ok']}")

    # ---- interleaved verdict rounds ----------------------------------
    per_round = {n: [] for n in sizes}
    for r in range(args.rounds):
        for n in sizes:
            arm = arms[n]
            state, step, batch = arm["state"], arm["step"], arm["batch"]
            t0 = time.perf_counter()
            for _ in range(args.steps):
                state, loss = step(state, *batch)
            jax.block_until_ready(loss)
            dt = time.perf_counter() - t0
            arm["state"] = state  # graftlint: disable=JGL001 -- ownership handoff, not a stale read: `state` was rebound from the donated call's result each iteration and the arms dict is its only holder between rounds
            ips = args.steps * arm["global_batch"] / dt
            per_round[n].append(round(ips, 3))
        print(f"round {r}: " + "  ".join(
            f"n={n}:{per_round[n][-1]:7.2f} img/s" for n in sizes))

    # ---- verdicts -----------------------------------------------------
    results = {}
    for n in sizes:
        med = statistics.median(per_round[n])
        results[n] = {
            "mesh": arms[n]["mesh"],
            "global_batch": arms[n]["global_batch"],
            "imgs_per_sec_rounds": per_round[n],
            "imgs_per_sec_median": round(med, 3),
            "per_device_imgs_per_sec": round(med / n, 3),
            "warm_compile_s": arms[n]["warm_s"],
            "state_leaves": arms[n]["sharding"],
            # the per-arm loss is over the arm's OWN global batch (weak
            # scaling grows the batch with n) — comparable parity lives
            # in the dedicated same-batch block below
            "first_step_loss": arms[n]["first_loss"],
            "first_step_finite": bool(np.isfinite(arms[n]["first_loss"])),
        }
    medians = [results[n]["imgs_per_sec_median"] for n in sizes]
    monotone = all(b >= a * (1.0 - args.tolerance)
                   for a, b in zip(medians, medians[1:]))
    eff = {n: round(results[n]["imgs_per_sec_median"]
                    / (medians[0] * n), 4) for n in sizes}

    artifact = {
        "config": args.config,
        "image_size": size,
        "batch_per_device": args.batch_per_device,
        "devices": sizes,
        "platform": platform,
        "partition_rules": {
            "name": args.rules,
            "fingerprint": rules_fingerprint(rules,
                                             min_shard_dim=min_shard),
            "min_shard_dim": min_shard},
        "steps_per_segment": args.steps,
        "rounds": args.rounds,
        "results": {str(n): results[n] for n in sizes},
        "imgs_per_sec_medians": medians,
        "weak_scaling_efficiency": {str(n): eff[n] for n in sizes},
        "loss_parity": parity,
        "monotone_tolerance": args.tolerance,
        "monotone_ok": bool(monotone),
        "protocol": "interleaved rounds (every mesh size timed per "
                    "round, chained donated steps, median-of-rounds "
                    "verdict); compile warm-up outside all timing "
                    "windows.  On a virtual CPU mesh all devices "
                    "timeshare the host cores, so the gated claim is "
                    "monotone GLOBAL throughput, not per-device "
                    "efficiency (see module docstring).",
    }
    for n in sizes:
        e = results[n]
        print(f"n={n}: median {e['imgs_per_sec_median']:7.2f} imgs/s  "
              f"({e['per_device_imgs_per_sec']:6.2f}/dev, eff {eff[n]:.0%})"
              f"  gb={e['global_batch']}")
    print(f"monotone_ok={monotone} (tolerance {args.tolerance:.0%}) "
          f"parity_ok={parity['ok']}")
    if args.out:
        with open(args.out, "w") as f:
            strict_dump(artifact, f, indent=2)
        print(f"wrote {args.out}")
    else:
        print(strict_dumps(artifact))
    if not parity["ok"]:
        raise SystemExit("partitioned-vs-single-device loss parity "
                         f"failed: rel {parity['rel_diff']}")
    if not monotone:
        raise SystemExit("weak-scaling curve is not monotone: "
                         f"{medians}")


if __name__ == "__main__":
    main()
