#!/usr/bin/env python
"""Export the jitted forward as a serialized jax.export artifact.

The artifact contains the StableHLO program + calling convention; a server
reloads it with ``jax.export.deserialize(blob).call(variables, images)``
without importing this package's model code.

    python tools/export_model.py --config canonical \
        --checkpoint checkpoints/epoch_99 --out posenet.jaxexport
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description="serialize the jitted forward")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--checkpoint", default=None,
                    help="orbax checkpoint dir (omit: fresh init — useful "
                         "for shape/ABI checks)")
    ap.add_argument("--size", type=int, default=None,
                    help="input H=W (default: the config's)")
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    import jax

    from improved_body_parts_tpu.utils import (
        apply_platform_env, export_serialized)
    apply_platform_env()

    import jax.numpy as jnp

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model

    cfg = get_config(args.config)
    size = args.size or cfg.skeleton.height
    model = build_model(cfg)
    imgs = jnp.zeros((1, size, size, 3), jnp.float32)
    if args.checkpoint:
        from improved_body_parts_tpu.train.checkpoint import (
            restore_checkpoint)

        payload = restore_checkpoint(args.checkpoint)
        variables = {"params": payload["params"],
                     "batch_stats": payload["batch_stats"]}
    else:
        variables = model.init(jax.random.PRNGKey(0), imgs, train=False)
    path = export_serialized(model, variables, imgs, args.out)
    print(f"exported {args.config} @{size}px -> {path} "
          f"({os.path.getsize(path):,} bytes)")


if __name__ == "__main__":
    main()
