#!/usr/bin/env python
"""Export a serving program as a serialized jax.export artifact, with a
graftaudit-fingerprinted manifest and an export GATE against the blessed
PROGRAM_AUDIT.json.

The artifact contains the StableHLO program + calling convention; a
server reloads it with ``jax.export.deserialize(blob).call(...)``
without importing this package's model code.  Three program families:

- ``--program forward``: the bare last-stack forward (the legacy
  artifact) — call ``(variables, images (N,H,W,3))``;
- ``--program compact``: the compact serve program for one padded
  bucket shape — call ``(variables, img, valid_h, valid_w)``;
- ``--program decode``: the FUSED end-to-end decode serve program
  (forward + compact extraction + greedy assembly — the cascade tiers'
  actual serving program); same calling convention as compact.

For compact/decode, ``--size`` is the PADDED bucket/lane shape (the
``serve.warmup`` precompile unit), rounded up to the predictor's bucket
multiple; ``--batch N`` exports the N-lane pow2-chunk program instead of
the singleton flush.  ``--dtype bf16`` casts the checkpoint's fp32
params to bf16 storage first — the quantized student artifact.

Every export writes ``<out>.manifest.json`` stamping the compiled
graftaudit fingerprint (flops, bytes, aliases, HLO instruction count —
``analysis.program.fingerprint``) of the EXACT program serialized.  With
``--audit-program <registry name>`` the export is GATED: the fingerprint
is diffed against that program's entry in the committed
PROGRAM_AUDIT.json and the export REFUSES on divergence — the audit
golden becomes a deploy gate, so an artifact whose compiled program
drifted from what was reviewed (a new transfer, a lost donation alias, a
cost jump) can never ship silently.  A golden recorded under a different
jax version gates as a warning (structural fingerprints are
version-exact), mirroring ``tools/program_audit.py``.

    python tools/export_model.py --config canonical \
        --checkpoint checkpoints/epoch_99 --out posenet.jaxexport
    python tools/export_model.py --config tiny_student --dtype bf16 \
        --program decode --size 128 \
        --audit-program student_serve_decode_b1 --out student.jaxexport
"""
import argparse
import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import strict_dump  # noqa: E402


def _load_golden_fingerprint(name: str):
    """Resolve the blessed entry for ``name`` — called BEFORE the
    expensive compile, so a missing/unblessed program refuses in
    seconds.  Returns (golden dict, its compiled fingerprint)."""
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    golden_path = os.path.join(root, "PROGRAM_AUDIT.json")
    if not os.path.exists(golden_path):
        raise SystemExit(f"--audit-program: no blessed golden at "
                         f"{golden_path} — run tools/program_audit.py "
                         "--bless first")
    with open(golden_path) as f:
        golden = json.load(f)
    entry = golden.get("programs", {}).get(name)
    if entry is None:
        raise SystemExit(
            f"--audit-program {name}: not in the blessed "
            "PROGRAM_AUDIT.json — register the program "
            "(analysis.program.registry) and bless it before exporting")
    golden_fp = entry.get("fingerprint", {}).get("compiled")
    if not golden_fp:
        raise SystemExit(
            f"--audit-program {name}: the golden entry has no "
            "compiled-level fingerprint — re-bless with "
            "tools/program_audit.py --bless (full compile sweep)")
    return golden, golden_fp


def _audit_gate(name: str, golden, golden_fp, fingerprint: dict,
                jax_version: str):
    """Diff ``fingerprint`` against the blessed golden entry; returns
    the gate-status string or raises SystemExit on divergence."""
    from improved_body_parts_tpu.analysis.program.config import (
        load_audit_config)
    from improved_body_parts_tpu.analysis.program.fingerprint import (
        compare_compiled)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_audit_config(root)
    drift = compare_compiled(golden_fp, fingerprint,
                             cfg.cost_tolerance_pct)
    if not drift:
        return "passed"
    fields = ", ".join(
        f"{d['field']} {d['golden']!r}->{d['current']!r}"
        + (f" ({d['drift_pct']}%)" if d.get("drift_pct") else "")
        for d in drift)
    if golden.get("jax_version") != jax_version:
        # structural fingerprints are version-exact; a cross-version
        # golden still gates, but as a warning (the program_audit rule)
        print(f"WARNING: fingerprint differs from the golden (recorded "
              f"under jax {golden.get('jax_version')}, running "
              f"{jax_version}): {fields}", file=sys.stderr)
        return "version-mismatch-warning"
    raise SystemExit(
        f"export REFUSED: compiled fingerprint of the exported program "
        f"diverges from the blessed '{name}' entry — {fields}. If the "
        "change is intentional, re-bless with tools/program_audit.py "
        "--bless and re-export.")


def main():
    ap = argparse.ArgumentParser(
        description="serialize a serving program (jax.export) with a "
                    "graftaudit-fingerprinted, gateable manifest")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--checkpoint", default=None,
                    help="orbax checkpoint dir (omit: fresh init — useful "
                         "for shape/ABI checks)")
    ap.add_argument("--size", type=int, default=None,
                    help="forward: input H=W (default: the config's); "
                         "compact/decode: the padded bucket shape, "
                         "rounded up to the predictor's bucket multiple")
    ap.add_argument("--program", default="forward",
                    choices=("forward", "compact", "decode"),
                    help="program family to export (decode = the fused "
                         "serve program the cascade tiers dispatch)")
    ap.add_argument("--batch", type=int, default=None,
                    help="compact/decode: export the N-lane pow2-chunk "
                         "batch program (default: the singleton-flush "
                         "program)")
    ap.add_argument("--dtype", default="fp32",
                    choices=("fp32", "bf16", "int8"),
                    help="parameter storage dtype of the artifact "
                         "(bf16 = the cast fast-tier artifact; int8 = "
                         "weight-only per-output-channel quantization "
                         "with the dequant chain folded into the "
                         "program; compute dtype follows the config "
                         "regardless)")
    ap.add_argument("--audit-program", default=None, metavar="NAME",
                    help="GATE the export on this registry program's "
                         "blessed PROGRAM_AUDIT.json entry: refuse when "
                         "the exported program's compiled fingerprint "
                         "diverges from the golden")
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    import jax

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import jax.numpy as jnp
    import numpy as np

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.utils.precision import apply_serve_dtype

    golden = golden_fp = None
    if args.audit_program:
        # fail fast on an unblessed program BEFORE paying the compile
        golden, golden_fp = _load_golden_fingerprint(args.audit_program)

    cfg = get_config(args.config)
    size = args.size or cfg.skeleton.height
    model = build_model(cfg)
    imgs = jnp.zeros((1, size, size, 3), jnp.float32)
    if args.checkpoint:
        from improved_body_parts_tpu.train.checkpoint import (
            restore_checkpoint)

        payload = restore_checkpoint(args.checkpoint)
        variables = {"params": payload["params"],
                     "batch_stats": payload["batch_stats"]}
    else:
        variables = model.init(jax.random.PRNGKey(0), imgs, train=False)
    # ONE construction site for the storage-dtype chain (bf16 cast or
    # int8 quantize+in-program-dequant) — the registry's abstract twins
    # apply the same transform, so fingerprints line up
    model, variables = apply_serve_dtype(args.dtype, model, variables)

    from jax import export as jexport

    if args.program == "forward":
        if args.batch is not None:
            raise SystemExit("--batch applies to the compact/decode "
                             "serve programs; the forward artifact is "
                             "batch-polymorphic by shape")

        def forward(variables, imgs):
            return model.apply(variables, imgs, train=False)[-1][0]

        fn = jax.jit(forward)
        call_args = (variables, imgs)
    else:
        from improved_body_parts_tpu.infer.predict import Predictor

        pred = Predictor(model, variables, cfg.skeleton)
        b = pred.bucket
        h = w = size + (-size) % b  # the padded bucket/lane shape
        program = (pred.decode_program if args.program == "decode"
                   else pred.compact_program)
        fn = program((h, w), batch=args.batch)
        if args.batch is None:
            call_args = (variables,
                         jnp.zeros((h, w, 3), jnp.float32),
                         np.int32(h), np.int32(w))
        else:
            n = int(args.batch)
            call_args = (variables,
                         jnp.zeros((n, h, w, 3), jnp.float32),
                         np.full((n,), h, np.int32),
                         np.full((n,), w, np.int32))
        size = h

    # the compiled graftaudit fingerprint of the EXACT program being
    # serialized — what the manifest stamps and the gate diffs
    from improved_body_parts_tpu.analysis.program.audit import (
        GRAFTAUDIT_VERSION, audit_ruleset_hash)
    from improved_body_parts_tpu.analysis.program.compiled import (
        compile_program)
    from improved_body_parts_tpu.analysis.program.fingerprint import (
        compiled_fingerprint)
    from improved_body_parts_tpu.analysis.program.registry import (
        BuiltProgram)

    info, _ = compile_program(BuiltProgram(fn=fn, args=call_args))
    fingerprint = compiled_fingerprint(info)

    gate_status = "not-gated (no --audit-program)"
    if args.audit_program:
        gate_status = _audit_gate(args.audit_program, golden, golden_fp,
                                  fingerprint, jax.__version__)

    exported = jexport.export(fn, platforms=["cpu", "tpu"])(*call_args)
    with open(args.out, "wb") as f:
        f.write(exported.serialize())

    with open(args.out, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    manifest = {
        "artifact": os.path.basename(args.out),
        "bytes": os.path.getsize(args.out),
        "sha256": digest,
        "config": args.config,
        "program": args.program,
        "size": size,
        "batch": args.batch,
        "params_dtype": args.dtype,
        "jax_version": jax.__version__,
        "graftaudit": {"version": GRAFTAUDIT_VERSION,
                       "ruleset": audit_ruleset_hash(),
                       "compiled_fingerprint": fingerprint},
        "audit_gate": {"program": args.audit_program,
                       "status": gate_status},
    }
    manifest_path = args.out + ".manifest.json"
    with open(manifest_path, "w") as f:
        strict_dump(manifest, f, indent=2)
    print(f"exported {args.config}/{args.program} @{size}px "
          f"dtype={args.dtype} -> {args.out} "
          f"({os.path.getsize(args.out):,} bytes); manifest "
          f"{manifest_path} (audit gate: {gate_status})")


if __name__ == "__main__":
    main()
