#!/usr/bin/env python
"""Training CLI.

One script covers the reference's three entry points — train.py (single
device), train_parallel.py (single-process multi-GPU DataParallel) and
train_distributed.py (multi-process NCCL DDP) — because under SPMD they are
the same program over different meshes.  Multi-host runs add
``--coordinator/--num-processes/--process-id`` (jax.distributed), the
TPU-native replacement for ``torch.distributed.launch``
(reference: train_distributed.py:69-84, README.md:104).

Example:
    python tools/train.py --config canonical --epochs 100 \
        --train-h5 data/coco_train_dataset512.h5 --workers 4
    python tools/train.py --swa --resume checkpoints/epoch_90  # SWA fine-tune
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import strict_dump  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description="IMHN pose training (SPMD)")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--train-h5", default=None)
    ap.add_argument("--val-h5", default=None)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--resume", default=None,
                    help="checkpoint path or 'auto' for the latest")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--input-pipeline", default=None,
                    choices=("shm", "pool", "sync"),
                    help="worker transport (default: the config's "
                         "input_pipeline, normally 'shm' — persistent "
                         "shared-memory ring workers; 'pool' is the retired "
                         "pickle-everything Pool path, 'sync' in-process)")
    ap.add_argument("--wire", default=None, choices=("uint8", "f32"),
                    help="image wire format (default: the config's "
                         "input_wire, normally 'uint8' — 4x fewer bytes "
                         "across IPC and host->device, normalized inside "
                         "the jitted step)")
    ap.add_argument("--lr", type=float, default=0.0,
                    help="override the config's per-device learning rate "
                         "(the framework-native equivalent of editing the "
                         "reference's config.py constants)")
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--no-focal", action="store_true",
                    help="plain L2 loss (the reference's L2 curriculum stage)")
    ap.add_argument("--swa", action="store_true",
                    help="SWA fine-tuning with cyclic LR and frozen BN "
                         "(reference: train_distributed_SWA.py)")
    ap.add_argument("--swa-freq", type=int, default=5)
    ap.add_argument("--swa-lr-max", type=float, default=1e-5,
                    help="cyclic LR peak (train_distributed_SWA.py:365)")
    ap.add_argument("--swa-lr-min", type=float, default=1e-6)
    ap.add_argument("--print-freq", type=int, default=None,
                    help="metric-readback window in steps (default: the "
                         "config's print_freq)")
    ap.add_argument("--save-freq", type=int, default=None,
                    help="checkpoint on epochs divisible by N (default: "
                         "the config's save_freq, normally 1); the final "
                         "epoch always saves")
    ap.add_argument("--eval-freq", type=int, default=None,
                    help="run the val pass on epochs divisible by N "
                         "(default: the config's eval_freq, normally 1); "
                         "the final epoch always evals")
    ap.add_argument("--sync-checkpoint", action="store_true",
                    help="disable async checkpointing (the train loop "
                         "then blocks on the full Orbax write each save "
                         "— the legacy behavior; tools/ckpt_bench.py "
                         "measures the difference)")
    ap.add_argument("--keep-last-n", type=int, default=None,
                    help="retention GC: keep only the last N committed "
                         "checkpoints, plus the best and milestones "
                         "(default: the config's keep_last_n; 0 keeps "
                         "everything)")
    ap.add_argument("--milestone-every", type=int, default=None,
                    help="retention GC: additionally keep every epoch "
                         "divisible by K (default: the config's "
                         "milestone_every; 0 disables)")
    ap.add_argument("--device-gt", type=int, default=0, metavar="MAX_PEOPLE",
                    help="synthesize GT heatmaps ON DEVICE inside the train "
                         "step from padded joints (value = max people per "
                         "sample); ~500x less host->device label traffic")
    ap.add_argument("--debug-overlays", action="store_true",
                    help="save a GT heatmap overlay of the first batch each "
                         "epoch under <checkpoint_dir>/overlays (the "
                         "reference's show_image debug display, "
                         "train.py:188-200)")
    ap.add_argument("--seed", type=int, default=0,
                    help="seed for parameter init and the data-pipeline "
                         "RNG ((seed, epoch, index) scheme) — vary for "
                         "seed-replicated runs")
    ap.add_argument("--telemetry-sink", default=None,
                    help="JSONL run-event stream (default: the config's "
                         "telemetry_sink; 'auto' = <checkpoint_dir>/"
                         "events.jsonl, '' disables). Fold it with "
                         "tools/telemetry_report.py")
    ap.add_argument("--telemetry-port", type=int, default=None,
                    help="live /metrics (Prometheus) + /snapshot (JSON) "
                         "+ /healthz endpoint port (default: the "
                         "config's telemetry_port; 0 = ephemeral, "
                         "-1 disables)")
    ap.add_argument("--telemetry-trace", default=None,
                    help="span-trace export path (default: the config's "
                         "telemetry_trace; 'auto' = <checkpoint_dir>/"
                         "trace.json, '' disables). Open the export at "
                         "ui.perfetto.dev or fold it with "
                         "tools/trace_report.py")
    ap.add_argument("--on-divergence", default=None,
                    choices=("warn", "halt", "skip_step"),
                    help="run-health sentinel policy on a non-finite "
                         "loss/grad-norm window (default: the config's "
                         "on_divergence): warn = record and continue, "
                         "halt = stop the run, skip_step = drop the "
                         "update inside the jitted step")
    # elastic training (train.supervisor; TRAINING.md §1c)
    ap.add_argument("--supervised", action="store_true",
                    help="run under the elastic run supervisor: "
                         "SIGTERM/SIGINT drain to the next step-window "
                         "boundary, failures are classified (preemption/"
                         "transient vs deterministic) with exponential "
                         "backoff and a bounded crash budget, resume is "
                         "automatic from the last committed checkpoint "
                         "(topology changes reshard), and --epochs "
                         "becomes the TOTAL epoch target the run "
                         "converges to across restarts")
    ap.add_argument("--max-restarts", type=int, default=24,
                    help="supervised: absolute bound on segments "
                         "(process lifetimes) of one logical run")
    ap.add_argument("--crash-budget", type=int, default=3,
                    help="supervised: consecutive no-progress failures "
                         "before the supervisor gives up")
    ap.add_argument("--backoff-base", type=float, default=1.0,
                    help="supervised: first retry backoff in seconds "
                         "(doubles per consecutive no-progress failure)")
    ap.add_argument("--backoff-max", type=float, default=60.0)
    ap.add_argument("--reshard", default=None,
                    choices=("adjust", "refuse"),
                    help="what to do when a checkpoint's stamped device "
                         "topology differs from the current mesh: "
                         "'adjust' re-places the state onto the new mesh "
                         "(global batch + world-size LR scaling follow "
                         "the new device count, reported loudly), "
                         "'refuse' errors out. Default: refuse for plain "
                         "resumes, adjust under --supervised")
    # heatmap distillation (train.distill; TRAINING.md "Distillation +
    # cascade")
    ap.add_argument("--distill-from", default=None, metavar="CKPT",
                    help="train THIS config as a distilled student: "
                         "load the teacher's checkpoint (an orbax epoch "
                         "dir) and blend the supervised focal-L2 with a "
                         "focal-L2 against the teacher's heatmaps, "
                         "alpha*gt + (1-alpha)*teacher, teacher forward "
                         "folded into the jitted step (frozen, "
                         "non-donated). Requires --teacher-config")
    ap.add_argument("--teacher-config", default=None,
                    help="config name of the TEACHER architecture the "
                         "--distill-from checkpoint was trained with "
                         "(the student is --config); skeletons must "
                         "match — only width/stacks may differ")
    ap.add_argument("--distill-alpha", type=float, default=None,
                    help="GT blend weight (default: the config's "
                         "distill_alpha, normally 0.5; 1.0 = plain "
                         "supervised training)")
    ap.add_argument("--distill-alpha-warmup", type=int, default=None,
                    metavar="STEPS",
                    help="ramp alpha linearly from 1.0 (pure GT) to "
                         "--distill-alpha over the first N steps "
                         "(default: the config's "
                         "distill_alpha_warmup_steps; 0 = constant)")
    # GSPMD partitioned training (parallel.partition; TRAINING.md §1d)
    ap.add_argument("--partition", action="store_true",
                    help="run the fully GSPMD-partitioned train step: "
                         "param/optimizer state sharded per the "
                         "partition ruleset (wide conv kernels over the "
                         "'model' mesh axis), batch over 'data', "
                         "activations sharding-constrained, per-host "
                         "contiguous-slab input sharding; the ruleset "
                         "hash is stamped into every checkpoint and a "
                         "resume under different rules is refused")
    ap.add_argument("--partition-rules", default=None,
                    help="named ruleset (default: the config's "
                         "partition_rules, normally 'imhn'; 'replicated' "
                         "is the explicit everything-replicated A/B arm)")
    ap.add_argument("--mesh-model", type=int, default=None,
                    help="'model' mesh-axis size (default: the config's "
                         "mesh_model_axis; data axis = devices // model)")
    ap.add_argument("--lr-batch-ref", type=int, default=None,
                    help="large-batch recipe: reference global batch the "
                         "base LR was tuned at — enables linear LR "
                         "scaling by global_batch/ref with a gradual "
                         "base->scaled warmup (default: the config's "
                         "lr_batch_ref; 0 keeps the per-device "
                         "world_size convention)")
    # multi-host (jax.distributed)
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    args = ap.parse_args()

    import signal

    # SIGTERM used to kill the process outright, bypassing the
    # try/finally teardown below (only exceptions reached it): a bare
    # `kill` lost the in-flight checkpoint write, leaked ring workers
    # and dropped the span trace.  Convert it to SystemExit so the
    # shutdown path always runs; --supervised replaces this with the
    # supervisor's draining handler (stop at the next window boundary).
    def _sigterm_exit(signum, frame):
        raise SystemExit(128 + signum)

    signal.signal(signal.SIGTERM, _sigterm_exit)

    import jax
    import jax.numpy as jnp

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()  # honour JAX_PLATFORMS even under a sitecustomize

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.data import CocoPoseDataset, batches
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.parallel import (
        barrier, initialize_distributed, make_mesh, mesh_topology,
        replicated)
    from improved_body_parts_tpu.train import (
        CheckpointManager, RunSupervisor, StopRequested, TopologyChanged,
        create_train_state, cyclic_swa_schedule, fit, latest_checkpoint,
        make_eval_step, make_optimizer, make_train_step, milestone_eval,
        reshard_on_topology_change, restore_checkpoint, start_swa,
        step_decay_schedule, swap_swa_params, update_swa)

    initialize_distributed(args.coordinator, args.num_processes,
                           args.process_id)
    cfg = get_config(args.config)
    if args.lr and args.swa:
        # the SWA stage runs its own cyclic schedule from
        # --swa-lr-max/--swa-lr-min; a silently ignored --lr would let the
        # user believe they fine-tuned at that rate
        raise SystemExit("--lr does not apply to the SWA stage; use "
                         "--swa-lr-max/--swa-lr-min instead")
    if (args.checkpoint_dir or args.lr or args.print_freq
            or args.on_divergence or args.save_freq or args.eval_freq
            or args.sync_checkpoint or args.keep_last_n is not None
            or args.milestone_every is not None or args.partition
            or args.partition_rules or args.mesh_model is not None
            or args.lr_batch_ref is not None
            or args.distill_alpha is not None
            or args.distill_alpha_warmup is not None):
        import dataclasses

        overrides = {}
        # partitioning and the large-batch recipe fold into the config:
        # the step program, the schedule and the topology stamp must
        # all derive from ONE process-symmetric source
        if args.partition:
            overrides["partition"] = True
        if args.partition_rules:
            overrides["partition_rules"] = args.partition_rules
        if args.mesh_model is not None:
            overrides["mesh_model_axis"] = args.mesh_model
        if args.lr_batch_ref is not None:
            overrides["lr_batch_ref"] = args.lr_batch_ref
        # the alpha schedule folds into the config: the jitted distill
        # step reads it at trace time (same rule as on_divergence)
        if args.distill_alpha is not None:
            overrides["distill_alpha"] = args.distill_alpha
        if args.distill_alpha_warmup is not None:
            overrides["distill_alpha_warmup_steps"] = \
                args.distill_alpha_warmup
        if args.checkpoint_dir:
            overrides["checkpoint_dir"] = args.checkpoint_dir
        if args.lr:
            overrides["learning_rate_per_device"] = args.lr
        if args.print_freq:
            # fit()/train_epoch read config.train.print_freq; a silently
            # ignored --print-freq also silences the per-window telemetry
            # records on epochs shorter than the default window
            overrides["print_freq"] = args.print_freq
        if args.on_divergence:
            # folded into the config (not just the sentinel) because the
            # skip_step policy is enforced INSIDE the jitted step, which
            # reads config.train.on_divergence at trace time
            overrides["on_divergence"] = args.on_divergence
        # checkpoint cadence/retention fold into the config so fit() and
        # the SWA stage read ONE source of truth (and the save decision
        # stays process-symmetric — it derives from argv/config only)
        if args.save_freq:
            overrides["save_freq"] = args.save_freq
        if args.eval_freq:
            overrides["eval_freq"] = args.eval_freq
        if args.sync_checkpoint:
            overrides["async_checkpoint"] = False
        if args.keep_last_n is not None:
            overrides["keep_last_n"] = args.keep_last_n
        if args.milestone_every is not None:
            overrides["milestone_every"] = args.milestone_every
        cfg = cfg.replace(train=dataclasses.replace(cfg.train, **overrides))

    if args.distill_from:
        # the distillation step composes with the replicated training
        # stack (supervisor/checkpoint/telemetry unchanged); the modes
        # that change the step's pytree or signature are excluded
        # loudly rather than silently ignored
        if not args.teacher_config:
            raise SystemExit("--distill-from requires --teacher-config "
                             "(the teacher checkpoint's architecture; "
                             "the student is --config)")
        if args.swa:
            raise SystemExit("--distill-from covers the main fit; run "
                             "the SWA stage on the distilled student "
                             "without it")
        if cfg.train.partition:
            raise SystemExit("--distill-from runs the replicated step "
                             "(the student is small — that is the "
                             "point); drop --partition")
        if args.device_gt:
            raise SystemExit("--distill-from needs host GT label maps "
                             "(the blend's supervised term); drop "
                             "--device-gt")
        # checked HERE, before any dataset/device work: the skeletons
        # must match channel for channel or the blend is meaningless
        if get_config(args.teacher_config).skeleton != cfg.skeleton:
            raise SystemExit(
                f"teacher config '{args.teacher_config}' has a "
                f"different skeleton than student '{args.config}' — "
                "distillation blends heatmaps channel for channel, the "
                "skeletons must match (only width/stacks may differ)")
    elif args.teacher_config or args.distill_alpha is not None \
            or args.distill_alpha_warmup is not None:
        raise SystemExit("--teacher-config/--distill-alpha/"
                         "--distill-alpha-warmup require --distill-from")
    if not cfg.train.partition and (args.mesh_model is not None
                                    or args.partition_rules):
        # these flags only take effect on the partitioned path — an
        # explicit flag silently doing nothing is worse than an error
        raise SystemExit("--mesh-model/--partition-rules require "
                         "--partition (or a config with partition=True)")
    # partition ruleset resolved ONCE, next to the config it came from:
    # the supervisor's resume check, the step program, the state
    # placement and the topology stamp all consume this one value
    partition_rules_resolved = None
    if cfg.train.partition:
        from improved_body_parts_tpu.parallel import get_ruleset

        partition_rules_resolved = get_ruleset(cfg.train.partition_rules)

    # elastic supervision (train.supervisor): created BEFORE telemetry so
    # the segment's run_id lands in the run_start header — that id is
    # what telemetry_report.py stitches the segments back together on
    reshard_policy = args.reshard or ("adjust" if args.supervised
                                      else "refuse")
    supervisor = None
    if args.supervised:
        if args.swa:
            # the SWA stage is a short, cheap fine-tune driven by its own
            # loop below; re-running it after a preemption is simpler
            # than supervising it
            raise SystemExit("--supervised covers the main fit only; run "
                             "the SWA stage unsupervised (it is short — "
                             "just relaunch it)")
        supervisor = RunSupervisor(
            cfg.train.checkpoint_dir, max_restarts=args.max_restarts,
            crash_budget=args.crash_budget,
            backoff_base_s=args.backoff_base,
            backoff_max_s=args.backoff_max, reshard=reshard_policy,
            is_lead_host=args.process_id == 0,
            rules=partition_rules_resolved)
        # classification of the previous segment's end + backoff happen
        # here, before any device work
        supervisor.open_segment({"argv": sys.argv[1:]})
        supervisor.install_signal_handlers()

    from improved_body_parts_tpu.obs import RunTelemetry, resolve_sink_path

    sink_cfg = (args.telemetry_sink if args.telemetry_sink is not None
                else cfg.train.telemetry_sink)
    sink_path = resolve_sink_path(sink_cfg, cfg.train.checkpoint_dir)
    if sink_path and args.process_id > 0:
        # one stream per process: co-located processes appending to the
        # shared "auto" path would interleave run_start headers with
        # different t=0 baselines and garble the report
        sink_path += f".p{args.process_id}"
    trace_cfg = (args.telemetry_trace if args.telemetry_trace is not None
                 else cfg.train.telemetry_trace)
    trace_path = resolve_sink_path(trace_cfg, cfg.train.checkpoint_dir,
                                   default_name="trace.json")
    if trace_path and args.process_id > 0:
        trace_path += f".p{args.process_id}"  # one timeline per process
    tele_port = (args.telemetry_port if args.telemetry_port is not None
                 else cfg.train.telemetry_port)
    # PROCESS-SYMMETRIC decision, taken from argv/config only (before
    # any per-process override): the health-instrumented step compiles a
    # DIFFERENT program, so every host of a multi-process run must make
    # the same choice — and a non-warn divergence policy needs the
    # sentinel running even when no sink/endpoint was configured, or
    # `--on-divergence halt` would be accepted and silently unenforced
    telemetry_wanted = bool(sink_path or trace_path or tele_port >= 0
                            or cfg.train.on_divergence != "warn")
    if args.process_id > 0:
        # the endpoint is lead-host-only: a fixed --telemetry-port would
        # EADDRINUSE-crash every co-located non-lead process at startup
        tele_port = -1
    telemetry = None
    if telemetry_wanted:
        telemetry = RunTelemetry(
            sink_path, http_port=(tele_port if tele_port >= 0 else None),
            run_meta={"tool": "train", "config": args.config,
                      "seed": args.seed, "process_id": args.process_id,
                      **({"run_id": supervisor.run_id,
                          "segment": supervisor.segment}
                         if supervisor is not None else {})},
            step_sample=cfg.train.telemetry_sample,
            trace_path=trace_path,
            on_divergence=cfg.train.on_divergence,
            grad_norm_limit=cfg.train.health_grad_norm_limit)
        if telemetry.server is not None:
            print(f"telemetry: {telemetry.server.url}/metrics")
    if supervisor is not None:
        # /healthz now reports running/draining/backing-off next to the
        # sentinel state, and the segment_start record (with the
        # previous segment's classification) enters the event stream
        supervisor.bind(telemetry)
    if args.process_id == 0:
        # run manifest: link the checkpoint dir to its event stream so
        # artifacts and telemetry cross-reference (bench.py does the same)
        os.makedirs(cfg.train.checkpoint_dir, exist_ok=True)
        import json

        manifest = {"tool": "train", "config": args.config,
                    "argv": sys.argv[1:],
                    "telemetry_events": sink_path,
                    "telemetry_trace": trace_path,
                    "on_divergence": cfg.train.on_divergence,
                    "telemetry_port": (telemetry.server.port
                                       if telemetry is not None
                                       and telemetry.server is not None
                                       else None)}
        if supervisor is not None:
            # merge, not overwrite: RUN.json also carries the run ledger
            # (run_id, segments) the supervisor owns across restarts
            supervisor.update_manifest(manifest)
        else:
            with open(os.path.join(cfg.train.checkpoint_dir, "RUN.json"),
                      "w") as f:
                strict_dump(manifest, f, indent=2)

    train_h5 = args.train_h5 or cfg.train.hdf5_train_data
    val_h5 = args.val_h5 or cfg.train.hdf5_val_data
    ds = CocoPoseDataset(train_h5, cfg, augment=True, seed=args.seed)
    if args.num_processes > 1 and val_h5 and not os.path.exists(val_h5):
        # eval is a collective: a host silently skipping it while others
        # enter eval_epoch leaves the job in mismatched collectives forever
        raise SystemExit(
            f"--val-h5 {val_h5} missing on this host; every host needs the "
            "file in a multi-process run (or drop --val-h5)")
    val_ds = (CocoPoseDataset(val_h5, cfg, augment=False)
              if os.path.exists(val_h5) else None)

    partitioned = cfg.train.partition
    if partitioned and args.swa:
        # the SWA swap grafts swa_params into the state, changing the
        # pytree the sharding rules were matched against; run the SWA
        # fine-tune on the replicated path (it is short and cheap)
        raise SystemExit("--partition covers the main fit only; run the "
                         "SWA stage without it")
    model_ax = cfg.train.mesh_model_axis if partitioned else 1
    mesh = make_mesh(model=model_ax) if model_ax > 1 else make_mesh()
    n_dev = int(mesh.devices.size)  # devices across ALL processes
    # the batch shards over the 'data' axis only — 'model'-axis devices
    # split tensors, not rows — so the data extent is the batch multiplier
    data_ax = n_dev // model_ax
    global_batch = cfg.train.batch_size_per_device * data_ax
    # each host loads only its slice; shard_batch assembles the global array
    host_batch = global_batch // args.num_processes
    steps_per_epoch = max(len(ds) // global_batch, 1)
    rules = partition_rules_resolved
    rules_hash = None
    if partitioned:
        from improved_body_parts_tpu.parallel import rules_fingerprint

        rules_hash = rules_fingerprint(rules)
    # per-host row assignment: the partitioned path uses contiguous
    # per-global-batch slabs so the assembled global batch is
    # bit-identical to a single-host run (data.host_batch_shard); the
    # replicated path keeps the historical strided shard
    input_shard = "batch" if partitioned else "strided"
    print(f"devices={n_dev} mesh=data:{data_ax},model:{model_ax} "
          f"global_batch={global_batch} host_batch={host_batch} "
          f"steps/epoch={steps_per_epoch}"
          + (f" partition_rules={cfg.train.partition_rules}"
             f"#{rules_hash}" if partitioned else ""))

    model = build_model(cfg)

    def swa_schedule(start_step=0):
        return cyclic_swa_schedule(steps_per_epoch, args.swa_freq,
                                   lr_max=args.swa_lr_max,
                                   lr_min=args.swa_lr_min,
                                   start_step=start_step)

    if args.swa:
        # provisional (start anchor unknown until resume resolves); rebuilt
        # below once start_epoch is known — opt_state structure is identical
        schedule = swa_schedule()
    elif cfg.train.lr_batch_ref > 0:
        # large-batch recipe ("Extremely Large Minibatch SGD"): linear
        # LR scaling by global_batch / lr_batch_ref with a gradual
        # base->scaled warmup — what makes the pod-slice batch
        # trainable, not just runnable
        from improved_body_parts_tpu.train import large_batch_schedule

        schedule = large_batch_schedule(cfg.train, steps_per_epoch,
                                        global_batch,
                                        use_warmup=not args.no_warmup)
    else:
        # data_ax counts batch-carrying devices across ALL processes
        # (jax.devices() is global under jax.distributed; the 'model'
        # axis splits tensors, not rows), so it IS the reference's
        # world_size LR multiplier (train_distributed.py:388) — no
        # extra num_processes factor.
        schedule = step_decay_schedule(cfg.train, steps_per_epoch,
                                       world_size=data_ax,
                                       use_warmup=not args.no_warmup)
    optimizer = make_optimizer(cfg, schedule)
    sample = jnp.zeros((global_batch, cfg.skeleton.height,
                        cfg.skeleton.width, 3))
    state_shardings = None
    if partitioned:
        from improved_body_parts_tpu.parallel import train_state_shardings

        # strict: a parameter the ruleset misses fails HERE, at build,
        # naming the leaf — never a silent replicate at pod scale
        state_shardings = train_state_shardings(model, cfg, optimizer,
                                                mesh, rules)
    state = create_train_state(model, cfg, optimizer,
                               jax.random.PRNGKey(args.seed), sample)
    # re-align ranks between the heavy per-host init compile above and
    # the FIRST collective placement below: per-host init/compile skew
    # can exceed the transport bring-up window (see parallel.barrier)
    barrier("pre_state_replication")
    if partitioned:
        from improved_body_parts_tpu.parallel import (
            shard_tree, sharding_summary)

        state = shard_tree(state, state_shardings)
        print(f"partitioned state: {sharding_summary(state_shardings)}")
    else:
        state = jax.device_put(state, replicated(mesh))

    start_epoch = 0
    resumed_swa = False
    best_loss = float("inf")
    resumed_from_epoch = None
    if supervisor is not None:
        # supervised runs ALWAYS auto-resume: restore_latest + topology
        # check + replicated re-placement onto the current mesh
        resumed = supervisor.resume(state, mesh, args.num_processes)
        if resumed is not None:
            state, meta, _change = resumed
            start_epoch = meta["epoch"] + 1
            best_loss = float(meta.get("best_loss", float("inf")))
            resumed_swa = state.swa_count is not None
            resumed_from_epoch = meta["epoch"]
            print(f"resumed from epoch {meta['epoch']} "
                  f"(run {supervisor.run_id} segment {supervisor.segment})")
    elif args.resume:
        path = (latest_checkpoint(cfg.train.checkpoint_dir)
                if args.resume == "auto" else args.resume)
        if path:
            state, meta = restore_checkpoint(path, state)
            try:
                # one policy implementation with the supervised path
                # (detection, refusal text, reshard-only-on-change rule,
                # partition-ruleset refusal)
                state, _ = reshard_on_topology_change(
                    state, meta, mesh, args.num_processes,
                    reshard_policy, path, rules=rules)
            except TopologyChanged as e:
                raise SystemExit(str(e)) from None
            start_epoch = meta["epoch"] + 1
            best_loss = float(meta.get("best_loss", float("inf")))
            resumed_swa = state.swa_count is not None
            print(f"resumed from {path} (epoch {meta['epoch']})")
    if args.swa:
        if not resumed_swa:
            # entering SWA from a plain checkpoint (or scratch): record the
            # anchor now; a resumed SWA checkpoint already carries it
            state = start_swa(state)
        # Anchor the cyclic-LR sawtooth to the step SWA STARTED at
        # (reference: epoch - start_epoch, train_distributed_SWA.py:365-366)
        # — persisted in the state, so an interrupted SWA run resumes
        # mid-cycle in phase.  state.step mirrors the optax schedule count
        # in every resume case (full checkpoints restore both together;
        # imported reference weights keep both at 0).
        anchor = (int(state.swa_start_step)
                  if state.swa_start_step is not None else int(state.step))
        if anchor:
            optimizer = make_optimizer(cfg, swa_schedule(anchor))

    if args.debug_overlays and args.device_gt:
        print("--debug-overlays needs host-side labels; "
              "skipped under --device-gt")
    use_focal = not args.no_focal
    # health scalar (global grad norm) exactly when the bundle runs —
    # `telemetry_wanted` is process-symmetric, so all hosts compile the
    # same step program; read back only at window readbacks
    with_health = telemetry_wanted
    if args.distill_from:
        # student distillation: the frozen teacher's forward folds into
        # the jitted step; its variables ride as a real (non-donated)
        # program argument bound outside the jit, so the loop still
        # sees the standard (state, *batch) contract and the
        # supervisor/checkpoint/telemetry stack is untouched
        from improved_body_parts_tpu.train import (
            bind_teacher, make_distill_train_step)

        teacher_cfg = get_config(args.teacher_config)
        teacher_model = build_model(teacher_cfg)
        payload = restore_checkpoint(args.distill_from)
        teacher_vars = jax.device_put(
            {"params": payload["params"],
             "batch_stats": payload["batch_stats"]}, replicated(mesh))
        print(f"distilling from {args.distill_from} "
              f"(teacher {args.teacher_config}, "
              f"alpha {cfg.train.distill_alpha}, "
              f"warmup {cfg.train.distill_alpha_warmup_steps} steps)")
        train_step = bind_teacher(
            make_distill_train_step(model, teacher_model, cfg, optimizer,
                                    use_focal=use_focal,
                                    health=with_health),
            teacher_vars)
    else:
        # SWA freezes BatchNorm (train_distributed_SWA.py:219-221)
        train_step = make_train_step(
            model, cfg, optimizer, use_focal=use_focal,
            freeze_bn=args.swa,
            device_gt=args.device_gt > 0,
            health=with_health,
            mesh=mesh if partitioned else None,
            rules=rules,
            state_shardings=state_shardings)
    eval_step = make_eval_step(model, cfg, use_focal=use_focal)
    is_lead = args.process_id == 0

    pipeline = args.input_pipeline or cfg.train.input_pipeline
    wire = args.wire or cfg.train.input_wire
    if args.workers <= 0:
        pipeline = "sync"
    train_ring = eval_ring = None
    if pipeline == "shm":
        # persistent ring: workers spawn ONCE and serve every epoch (the
        # whole point — the retired Pool path re-paid pickling per sample;
        # the transient batches(pipeline="shm") facade re-pays spawn per
        # epoch)
        from improved_body_parts_tpu.data import ShmRingInput

        train_ring = ShmRingInput(ds, host_batch, args.workers,
                                  raw_gt=args.device_gt, wire=wire,
                                  slots=cfg.train.input_ring_slots,
                                  supervise=args.supervised)
        if val_ds is not None:
            eval_ring = ShmRingInput(val_ds, host_batch, args.workers,
                                     wire=wire,
                                     slots=cfg.train.input_ring_slots,
                                     supervise=args.supervised)
        if telemetry is not None:
            train_ring.attach_telemetry(telemetry.registry)
            if eval_ring is not None:
                eval_ring.attach_telemetry(telemetry.registry,
                                           prefix="eval_input_ring")

    def make_train_batches(epoch):
        if train_ring is not None:
            it = train_ring.batches(epoch, args.process_id,
                                    args.num_processes, shard=input_shard)
        else:
            it = batches(ds, host_batch, epoch, args.process_id,
                         args.num_processes, num_workers=args.workers,
                         raw_gt=args.device_gt, pipeline=pipeline, wire=wire,
                         shard=input_shard)
        if not (args.debug_overlays and is_lead) or args.device_gt:
            return it

        def with_overlay():
            from improved_body_parts_tpu.utils import save_batch_overlays

            overlay_dir = os.path.join(cfg.train.checkpoint_dir, "overlays")
            os.makedirs(overlay_dir, exist_ok=True)
            for i, (images, mask, labels) in enumerate(it):
                if i == 0:
                    sk = cfg.skeleton
                    save_batch_overlays(
                        os.path.join(overlay_dir, f"epoch_{epoch}.png"),
                        images, labels,
                        channels=(sk.bkg_start, sk.heat_start))
                yield images, mask, labels

        return with_overlay()

    make_eval_batches = None
    if val_ds is not None:
        def make_eval_batches(epoch):
            if eval_ring is not None:
                return eval_ring.batches(0, args.process_id,
                                         args.num_processes,
                                         shard=input_shard)
            return batches(val_ds, host_batch, 0, args.process_id,
                           args.num_processes, num_workers=args.workers,
                           pipeline=pipeline, wire=wire, shard=input_shard)

    # ONE checkpoint manager for both stages (fit and SWA): async
    # snapshot + background Orbax write + atomic commit markers +
    # retention GC, from the config knobs (process-symmetric).  The mesh
    # topology rides every commit marker so a restart on a different
    # device layout is detected at restore time, not mid-step.
    # the partition-ruleset hash rides the topology stamp: a resume
    # under different rules is then a refused layout change, exactly
    # like a different device count (train.supervisor)
    manager = CheckpointManager.from_config(
        cfg.train.checkpoint_dir, cfg.train, is_lead_host=is_lead,
        topology=mesh_topology(mesh, partition_rules=rules_hash))

    def shutdown():
        # flush the in-flight checkpoint write FIRST: its commit event
        # must land in the sink before telemetry closes, and the ring
        # teardown must not outrun a write that still reads host
        # buffers.  Best-effort — the happy paths already surfaced
        # writer errors via fit's / the SWA loop's wait(); a failure
        # HERE must not mask the exception this finally is unwinding
        try:
            manager.close()
        except Exception as e:  # noqa: BLE001
            print(f"checkpoint flush failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
        for ring in (train_ring, eval_ring):
            if ring is not None:
                ring.close()
        if telemetry is not None:
            telemetry.close()
        if args.num_processes > 1:
            jax.distributed.shutdown()  # aligned exit across processes

    epochs = args.epochs or cfg.train.epochs
    # second alignment: resume/restore and step-function setup add more
    # per-host skew before the first step's collective execution
    barrier("pre_train_loop")
    # try/finally, not sequential calls: a crash — and especially a
    # sentinel halt (obs.DivergenceError) — must still close telemetry
    # (the ONLY place the span trace is saved; losing trace.json on the
    # very run that diverged would defeat the forensics), stop the ring
    # workers, and keep the multi-host jax.distributed exit aligned
    try:
        if not args.swa:
            if supervisor is None:
                fit(state, train_step, cfg, make_train_batches, epochs,
                    start_epoch=start_epoch, mesh=mesh, eval_step=eval_step,
                    make_eval_batches=make_eval_batches, is_lead_host=is_lead,
                    best_loss=best_loss, telemetry=telemetry,
                    checkpoint_manager=manager)
                return

            # ---- supervised elastic fit: --epochs is the TOTAL target
            # the logical run converges to across restarts
            def fresh_state():
                s = create_train_state(model, cfg, optimizer,
                                       jax.random.PRNGKey(args.seed), sample,
                                       shardings=state_shardings)
                if state_shardings is None:
                    s = jax.device_put(s, replicated(mesh))
                return s

            def resume_milestone(epoch):
                # lightweight eval right after a restore: recovery
                # correctness as a number in the stream, not a hope.
                # Collective and argv-symmetric (every process takes the
                # same branch)
                if eval_step is None or make_eval_batches is None:
                    return
                loss = milestone_eval(state, eval_step,
                                      make_eval_batches(epoch), mesh=mesh)
                if telemetry is not None:
                    telemetry.emit("resume_eval", epoch=epoch,
                                   loss=round(float(loss), 6))
                if is_lead:
                    print(f"resume milestone eval (after epoch {epoch}): "
                          f"loss {loss:.6f}")

            if resumed_from_epoch is not None:
                resume_milestone(resumed_from_epoch)
            target = epochs
            while True:
                to_run = target - start_epoch
                if to_run <= 0:
                    if is_lead:
                        print(f"supervisor: epoch target {target} already "
                              "reached — nothing to train")
                    supervisor.mark_completed()
                    return
                try:
                    fit(state, train_step, cfg, make_train_batches, to_run,
                        start_epoch=start_epoch, mesh=mesh,
                        eval_step=eval_step,
                        make_eval_batches=make_eval_batches,
                        is_lead_host=is_lead, best_loss=best_loss,
                        telemetry=telemetry, checkpoint_manager=manager,
                        should_stop=supervisor.should_stop)
                except StopRequested as e:
                    # fit already flushed the in-flight write; the
                    # finally below exports the trace and stops the ring
                    supervisor.close_segment("preempted", str(e))
                    if is_lead:
                        print(f"supervisor: clean stop — {e}")
                    return
                except Exception as e:
                    # transient -> backoff happened inside on_failure;
                    # deterministic (or budget exhausted) -> recorded as
                    # crashed and re-raised
                    if supervisor.on_failure(e) != "retry":
                        raise
                    resumed = supervisor.resume(state, mesh,
                                                args.num_processes)
                    if resumed is None:
                        # failed before the first commit: restart the
                        # segment from the deterministic initial state
                        state = fresh_state()
                        start_epoch, best_loss = 0, float("inf")
                    else:
                        state, meta, _change = resumed
                        start_epoch = meta["epoch"] + 1
                        best_loss = float(meta.get("best_loss",
                                                   float("inf")))
                        resume_milestone(meta["epoch"])
                    continue
                supervisor.mark_completed()
                return

        # SWA fine-tune: average params every swa_freq epochs, swap
        # averaged params in for the checkpoint (reference:
        # train_distributed_SWA.py:403-435)
        from improved_body_parts_tpu.train.loop import _log_line, train_epoch

        if resumed_swa:
            # SWA checkpoints are saved swapped (params=averaged,
            # swa_params=live SGD weights); swap back to continue training
            # from the live weights while keeping the running average
            # intact.  (start_swa already ran above when entering SWA
            # fresh.)
            state = swap_swa_params(state)
        for epoch in range(start_epoch, start_epoch + epochs):
            state, train_loss = train_epoch(
                state, train_step, make_train_batches(epoch), cfg, epoch,
                mesh=mesh, is_lead_host=is_lead, telemetry=telemetry)
            if is_lead:
                # same append-only epoch log fit() writes (reference logs
                # its SWA epochs too, train_distributed_SWA.py) — without
                # it the SWA stage leaves no loss provenance for the
                # artifacts
                _log_line(cfg.train.checkpoint_dir,
                          f"\nEpoch {epoch}\ttrain_loss: {train_loss}")
            if (epoch - start_epoch + 1) % args.swa_freq == 0:
                state = update_swa(state)
                # collective ASYNC save (orbax barriers across processes
                # on the writer threads; manager.save blocks only on the
                # snapshot drain, the write overlaps the next SWA epochs)
                swapped = swap_swa_params(state)
                manager.save(swapped, epoch, train_loss, train_loss)
                if is_lead:
                    print(f"epoch {epoch}: SWA checkpoint saved")
        if epochs and epochs % args.swa_freq:
            # trailing epochs past the last freq boundary: average and
            # save them too, or they train but are never part of any
            # checkpoint and the eval silently scores the older
            # freq-boundary save (ADVICE.md round 5,
            # tools/tpu_train_session.py stale-checkpoint guard)
            state = update_swa(state)
            swapped = swap_swa_params(state)
            manager.save(swapped, epoch, train_loss, train_loss)
            if is_lead:
                print(f"epoch {epoch}: final SWA checkpoint saved "
                      f"({epochs % args.swa_freq} trailing epochs)")
        # surface a trailing writer failure HERE, on the happy path —
        # shutdown()'s flush is best-effort by design
        manager.wait()
    finally:
        shutdown()


if __name__ == "__main__":
    main()
