#!/usr/bin/env python
"""Fault-injection harness for fault-tolerant serving (serve.pool).

The serve-side twin of ``tools/chaos_train.py``: a replica pool
(``serve.EnginePool`` over shared-nothing ``DynamicBatcher`` replicas)
serves real traffic — single-image submits, policy-layer hedged
submits, and live stream sessions — while deterministic faults are
injected INTO the serving machinery:

- **wedged fetcher**: a replica's device resolve parks forever — the
  health probe must see the stall, fence the replica, and the bounded
  drain must fail its in-flight work over to a healthy replica;
- **poisoned program**: a replica's execute raises mid-flight until its
  failure-rate circuit breaker trips — callers must never see the
  failures (failover), the replica is fenced, and after restart it
  re-enters through HALF-OPEN probation and closes its breaker;
- **killed decode pool**: a host-pool-lane replica's decode executor is
  shut down out from under it — the fetcher's inline-decode fallback
  must keep the replica serving (degraded, not dead: no fence);
- **replica hard-stop mid-stream**: a replica is stopped abruptly while
  live ``StreamSession`` traffic is pinned on it — the pool re-submits
  the stranded frames and every stream must deliver ALL frames strictly
  in order (the tracker's age stamp is the proof);
- **latency spike**: a replica turns slow — the policy layer's hedged
  second dispatch must bound the tail (hedges fire and win);
- **worker SIGKILL**: the process lane — a ``ProcessRouter`` worker
  PROCESS is kill -9'd mid-batch.  The shm-wire engine fails its
  in-flight futures with ``WorkerDied``, the pool fences the replica
  and fails the work over (zero lost futures), and the supervisor
  respawns a FRESH process (new pid) that serves again;
- **fastpath mid-skip-run**: streams running the temporal-coherence
  fast path (``stream.fastpath``: tracker-tier frame skipping) hit the
  full fault menu MID-SKIP-RUN — a shed under drop_oldest backpressure,
  a live migration of parked real forwards to a healthy replica, and a
  replica hard-stop that strands a real forward (the ``error``
  escalation re-proves the scene before skipping resumes).  The
  three-tier conservation ledger (``submitted == answered_tracker +
  answered_roi + escalated_full + failed + dropped + depth``) must
  balance EXACTLY through all of it.

Asserted end to end, the ISSUE 11 acceptance: **zero lost futures**
(every submit() of any kind resolves with a result or a typed error),
**bounded failover time**, **frame-order-preserving migration**, a
thread/descendant **leak scan**, and a **0 post-warmup recompile** count
per replica (the pool's program warmup covers every shape the chaos
traffic can hit).

Writes SERVE_CHAOS.json; registered as bench.py's ``"servechaos"`` key
(``IBP_BENCH_SERVECHAOS=0`` skips).

    python tools/chaos_serve.py                          # full sweep
    python tools/chaos_serve.py --requests 4 --frames 6  # smoke
"""
import argparse
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


# ------------------------------------------------------------ chaos preds
class ChaosBox:
    """Per-replica fault controls, armed/disarmed by the harness."""

    def __init__(self):
        self.lock = threading.Lock()
        self.wedge = threading.Event()   # set = wedged
        self.release = threading.Event()  # set = wedged resolves may pass
        self.poison_left = 0
        self.delay_s = 0.0

    def apply(self):
        """Runs INSIDE a wrapped resolve(), on the replica's fetch
        thread — the mid-execute injection point."""
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        if self.wedge.is_set():
            self.release.wait()          # parks the fetcher
        with self.lock:
            if self.poison_left > 0:
                self.poison_left -= 1
                raise RuntimeError(
                    "chaos: poisoned program raised mid-execute")


class ChaosPredictor:
    """Wraps a real Predictor; every async dispatch's resolve() first
    passes through the replica's ChaosBox (wedge / poison / delay land
    mid-execute, exactly where a sick device or program would)."""

    def __init__(self, inner, box: ChaosBox):
        self._inner, self._box = inner, box

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _wrap(self, resolve):
        def wrapped():
            self._box.apply()
            return resolve()

        return wrapped

    def predict_compact_async(self, *a, **kw):
        return self._wrap(self._inner.predict_compact_async(*a, **kw))

    def predict_compact_batch_async(self, *a, **kw):
        return self._wrap(
            self._inner.predict_compact_batch_async(*a, **kw))

    def predict_decoded_async(self, *a, **kw):
        return self._wrap(self._inner.predict_decoded_async(*a, **kw))

    def predict_decoded_batch_async(self, *a, **kw):
        return self._wrap(
            self._inner.predict_decoded_batch_async(*a, **kw))


class LedgeredFutures:
    """Every future the harness ever hands out, so 'zero lost futures'
    is a checkable number, not a vibe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._futs = []

    def track(self, fut, kind):
        with self._lock:
            self._futs.append((fut, kind))
        return fut

    def audit(self, timeout_s=30.0):
        deadline = time.monotonic() + timeout_s
        unresolved = []
        ok = err = 0
        by_error = {}
        with self._lock:
            futs = list(self._futs)
        for fut, kind in futs:
            remaining = max(0.0, deadline - time.monotonic())
            try:
                fut.result(timeout=remaining)
                ok += 1
            except Exception as e:  # noqa: BLE001 — typed errors are a
                # RESOLUTION; only a never-resolving future is a loss
                if fut.done():
                    err += 1
                    name = type(e).__name__
                    by_error[name] = by_error.get(name, 0) + 1
                else:
                    unresolved.append(kind)
        return {"tracked": len(futs), "resolved_ok": ok,
                "resolved_error": err, "errors_by_type": by_error,
                "lost": len(unresolved), "lost_kinds": unresolved}


def wait_until(pred, timeout_s=30.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--size", type=int, default=128,
                    help="square image size of the chaos traffic")
    ap.add_argument("--boxsize", type=int, default=128)
    ap.add_argument("--replicas", type=int, default=3,
                    help="pool replicas (>= 2 so failover has a target; "
                         "replica 1 runs the host-pool decode lane for "
                         "the killed-decode-pool injection)")
    ap.add_argument("--requests", type=int, default=6,
                    help="requests per single-image injection phase")
    ap.add_argument("--streams", type=int, default=2)
    ap.add_argument("--frames", type=int, default=8,
                    help="frames per stream in the hard-stop phase")
    ap.add_argument("--planted", type=int, default=1)
    ap.add_argument("--wedge-timeout", type=float, default=8.0,
                    help="pool wedge_timeout_s (stall age before fence); "
                         "must sit WELL above the host's burst-case "
                         "batch service time or a merely-busy replica "
                         "gets false-fenced — on this harness's 2-core "
                         "class hosts, replica forwards contend for the "
                         "same cores, so the margin is generous")
    ap.add_argument("--failover-bound", type=float, default=60.0,
                    help="per-injection wall bound on full recovery")
    ap.add_argument("--out", default="SERVE_CHAOS.json")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when any assertion fails")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import numpy as np

    platform = devices_with_timeout(900)[0].platform
    print(f"platform={platform}", flush=True)

    from e2e_bench import PlantedModel, planted_maps

    from improved_body_parts_tpu.config import (
        InferenceModelParams, get_config)
    from improved_body_parts_tpu.infer.predict import Predictor
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs import Registry, RunTelemetry
    from improved_body_parts_tpu.serve import (
        DynamicBatcher, EnginePool, PolicyClient)
    from improved_body_parts_tpu.stream import (
        FastPathConfig, SessionManager)

    import jax.numpy as jnp

    cfg = get_config(args.config)
    model = build_model(cfg)
    rng = np.random.default_rng(0)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, args.size, args.size, 3)),
                           train=False)
    if args.planted > 0:
        # canvas == frame size hugs the planted crowd into the frame's
        # top-left, so it actually DECODES at chaos smoke sizes — the
        # hard-stop order proof (tracker age stamps) and the fastpath
        # phase (a tracker needs confirmed tracks to skip) both need
        # real people, not an empty decode
        model = PlantedModel(model, planted_maps(cfg.skeleton,
                                                 args.planted, rng,
                                                 canvas=args.size),
                             cfg.skeleton)
    model_params = (InferenceModelParams(boxsize=args.boxsize)
                    if args.boxsize else None)

    n_rep = max(2, args.replicas)
    boxes = [ChaosBox() for _ in range(n_rep)]
    # shared-nothing replicas: one Predictor per replica (never two
    # dispatchers driving one program cache), each wrapped in its chaos
    # controls.  Replica 1 runs the pre-fusion HOST-POOL decode lane so
    # the killed-decode-pool injection targets a load-bearing executor.
    preds = [ChaosPredictor(
        Predictor(model, variables, cfg.skeleton,
                  model_params=model_params), boxes[i])
        for i in range(n_rep)]
    engines = [DynamicBatcher(preds[i], max_batch=2, max_wait_ms=15,
                              max_queue=64, decode_workers=2,
                              device_decode=(i != 1))
               for i in range(n_rep)]

    sink_path = os.path.splitext(args.out)[0] + "_events.jsonl"
    telemetry = RunTelemetry(
        sink_path, registry=Registry(),
        run_meta={"tool": "chaos_serve", "config": args.config,
                  "platform": platform})

    img = np.zeros((args.size, args.size, 3), np.uint8)
    ledger = LedgeredFutures()
    report = {
        "protocol": (
            "in-process EnginePool over shared-nothing DynamicBatcher "
            "replicas serving real traffic (submits, hedged policy "
            "submits, stream sessions) while deterministic faults are "
            "injected mid-execute, plus a process-lane phase (worker "
            "SIGKILL through a ProcessRouter); every future tracked; "
            "zero-lost/bounded-failover/frame-order/leak-scan "
            "asserted"),
        "platform": platform, "config": args.config,
        "size": args.size, "replicas": n_rep,
        "requests_per_phase": args.requests,
        "streams": args.streams, "frames_per_stream": args.frames,
        "wedge_timeout_s": args.wedge_timeout,
        "telemetry_events": sink_path,
        "injections": [],
    }
    failures = []

    def check(cond, label):
        print(("PASS " if cond else "FAIL ") + label, flush=True)
        if not cond:
            failures.append(label)
        return bool(cond)

    # thread/descendant baseline BEFORE any serving machinery exists
    threads_before = {t.ident for t in threading.enumerate()}

    def proc_children():
        out = []
        for name in os.listdir("/proc"):
            if not name.isdigit():
                continue
            try:
                with open(f"/proc/{name}/stat") as f:
                    if int(f.read().split()[3]) == os.getpid():
                        out.append(int(name))
            except (OSError, IndexError, ValueError):
                continue
        return out

    children_before = set(proc_children())

    pool = EnginePool(
        engines, probe_interval_s=0.05,
        wedge_timeout_s=args.wedge_timeout, drain_timeout_s=1.0,
        breaker_kw=dict(failure_threshold=0.5, min_requests=4,
                        window=8, cooldown_s=1.0, half_open_probes=1),
        registry=telemetry.registry)
    pool.start()
    warm = pool.warmup([(args.size, args.size)])
    # untimed warm slice over every traffic shape the phases use (pool
    # submits + stream frames), then arm the recompile watch: any
    # compile past this line is a failing number
    for f in [pool.submit(img) for _ in range(n_rep * 2)]:
        f.result(timeout=600)
    with SessionManager(pool, max_in_flight=3) as warm_mgr:
        ws = warm_mgr.open("warm")
        for f in [ws.submit_frame(img) for _ in range(4)]:
            f.result(timeout=600)
    telemetry.mark_warm("pool warmup + warm slice")
    report["warmup"] = {"newly_compiled": warm["newly_compiled"],
                        "bucket_shapes": [list(s) for s in
                                          warm["bucket_shapes"]],
                        "batch_sizes": list(warm["batch_sizes"])}
    check(all(s["state"] == "live" for s in pool.replica_states()),
          "warm pool: every replica live before the first injection "
          "(no false wedge-fence under ordinary load)")

    # ---------------------------------------------------- 1: wedged fetcher
    def inject_wedged_fetcher():
        t0 = time.perf_counter()
        boxes[0].wedge.set()
        futs = [ledger.track(pool.submit(img), "wedged_fetcher")
                for _ in range(args.requests)]
        results = [f.result(timeout=300) for f in futs]
        recovered_s = time.perf_counter() - t0
        fenced = wait_until(
            lambda: pool.replica_states()[0]["state"] == "fenced",
            timeout_s=30)
        reason = pool.replica_states()[0]["fence_reason"]
        boxes[0].wedge.clear()
        boxes[0].release.set()           # unpin the parked fetcher
        time.sleep(0.1)
        boxes[0].release.clear()
        restarted = pool.restart(0)
        rec = {
            "kind": "wedged_fetcher", "futures": len(futs),
            "all_resolved_ok": all(isinstance(r, list) for r in results),
            "fenced": fenced, "fence_reason": reason,
            "restarted": restarted,
            "recovery_s": round(recovered_s, 3),
        }
        check(rec["all_resolved_ok"], "wedged: every future resolved ok")
        check(fenced and reason in ("wedged", "stopped"),
              "wedged: replica fenced by the health probe")
        check(recovered_s < args.failover_bound,
              f"wedged: recovery bounded ({recovered_s:.2f}s)")
        check(restarted, "wedged: replica restarted into routing")
        return rec

    # -------------------------------------------------- 2: poisoned program
    def inject_poisoned_program():
        t0 = time.perf_counter()
        with boxes[0].lock:
            boxes[0].poison_left = 2 * args.requests
        # sequential closed loop: at submit time every replica is idle,
        # so the depth tie deterministically routes each first attempt
        # to replica 0 — the poisoned one — until the breaker fences it
        futs = []
        for _ in range(args.requests):
            f = ledger.track(pool.submit(img), "poisoned_program")
            f.result(timeout=300)        # raises = a LOST failover
            futs.append(f)
        recovered_s = time.perf_counter() - t0
        fenced = wait_until(
            lambda: pool.replica_states()[0]["state"] == "fenced",
            timeout_s=30)
        reason = pool.replica_states()[0]["fence_reason"]
        with boxes[0].lock:
            boxes[0].poison_left = 0     # the program "heals"
        restarted = pool.restart(0)
        breaker_after_restart = pool.replica_states()[0]["breaker"]
        # half-open probation: traffic closes the breaker again
        probed = [ledger.track(pool.submit(img), "poison_probe")
                  for _ in range(4)]
        for f in probed:
            f.result(timeout=300)
        closed = wait_until(
            lambda: pool.replica_states()[0]["breaker"] == "closed",
            timeout_s=30)
        rec = {
            "kind": "poisoned_program", "futures": len(futs) + len(probed),
            "fenced": fenced, "fence_reason": reason,
            "restarted": restarted,
            "breaker_after_restart": breaker_after_restart,
            "breaker_closed_after_probes": closed,
            "recovery_s": round(recovered_s, 3),
        }
        check(fenced and reason == "breaker_open",
              "poison: breaker tripped and fenced the replica")
        check(breaker_after_restart == "half_open",
              "poison: restart re-enters through half-open probation")
        check(closed, "poison: probes closed the breaker")
        check(recovered_s < args.failover_bound,
              f"poison: recovery bounded ({recovered_s:.2f}s)")
        return rec

    # ------------------------------------------------ 3: killed decode pool
    def inject_killed_decode_pool():
        # replica 1 is the host-pool decode lane: its executor is load-
        # bearing.  Kill it; the fetcher's inline-decode fallback must
        # keep the replica serving — degraded, NOT fenced.
        before = engines[1].metrics.completed
        engines[1]._pool.shutdown(wait=False)
        futs = [ledger.track(
            engines[1].submit(img), "killed_decode_pool")
            for _ in range(args.requests)]
        results = [f.result(timeout=300) for f in futs]
        ok = all(isinstance(r, list) for r in results)
        still_live = pool.replica_states()[1]["state"] == "live"
        rec = {
            "kind": "killed_decode_pool", "futures": len(futs),
            "all_resolved_ok": ok,
            "completed_before": before,
            "completed_after": engines[1].metrics.completed,
            "replica_still_live": still_live,
        }
        check(ok, "decode-pool: inline fallback served every request")
        check(still_live,
              "decode-pool: degraded replica stays live (no fence)")
        return rec

    # --------------------------------------- 4: replica hard-stop mid-stream
    def inject_hard_stop_mid_stream():
        t0 = time.perf_counter()
        resub_before = pool.counters()["resubmitted"]
        # slow replica 0 down so frames are deterministically pinned on
        # it when the hard-stop lands (otherwise a fast host could have
        # drained it and the stop would strand nothing)
        boxes[0].delay_s = 0.3
        mgr = SessionManager(pool, max_in_flight=3)
        sessions = [mgr.open(f"chaos{i}") for i in range(args.streams)]
        stop_at = args.frames // 2
        per_stream = []

        def client(si):
            s = sessions[si]
            futs = []
            for t in range(args.frames):
                if si == 0 and t == stop_at:
                    # hard-stop a replica while frames are pinned on it
                    engines[0].stop(drain_timeout_s=0.1)
                futs.append(ledger.track(s.submit_frame(img),
                                         "stream_frame"))
            ordered = True
            delivered = 0
            for i, f in enumerate(futs):
                tracked = f.result(timeout=300)
                delivered += 1
                # static planted crowd: the tracker age stamp equals the
                # frame index IFF every earlier frame was delivered, in
                # order, exactly once — the frame-order proof
                if not all(p.age == i for p in tracked):
                    ordered = False
            per_stream.append({"stream": si, "delivered": delivered,
                               "ordered": ordered})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(args.streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        boxes[0].delay_s = 0.0
        recovered_s = time.perf_counter() - t0
        fenced = wait_until(
            lambda: pool.replica_states()[0]["state"] == "fenced",
            timeout_s=30)
        snaps = [s.snapshot() for s in sessions]
        mgr.close_all(timeout_s=60)
        restarted = pool.restart(0)
        resubmitted = pool.counters()["resubmitted"] - resub_before
        rec = {
            "kind": "replica_hard_stop_mid_stream",
            "streams": per_stream,
            "fenced": fenced,
            "fence_reason": pool.replica_states()[0]["fence_reason"],
            "restarted": restarted,
            "frames_failed": sum(s["frames_failed"] for s in snaps),
            "frames_resubmitted": resubmitted,
            "recovery_s": round(recovered_s, 3),
        }
        check(all(p["delivered"] == args.frames for p in per_stream),
              "hard-stop: every stream delivered every frame")
        check(all(p["ordered"] for p in per_stream),
              "hard-stop: frame order preserved across migration")
        check(rec["frames_failed"] == 0,
              "hard-stop: zero frame failures (failover was invisible)")
        check(resubmitted >= 1,
              "hard-stop: stranded in-flight frames were re-submitted")
        check(recovered_s < args.failover_bound,
              f"hard-stop: recovery bounded ({recovered_s:.2f}s)")
        check(restarted, "hard-stop: replica restarted into routing")
        return rec

    # ------------------------------------------------------ 5: latency spike
    def inject_latency_spike():
        boxes[0].delay_s = 0.4
        client = PolicyClient(pool, hedge_after_s=0.1, max_attempts=6)
        client.stats.register_into(telemetry.registry)
        # sequential closed loop: every primary lands on the (idle,
        # tie-preferred) slow replica, so every request exercises the
        # hedge path against a healthy one
        futs, lat = [], []
        for _ in range(args.requests):
            t0 = time.perf_counter()
            f = ledger.track(client.submit(img), "hedged_submit")
            f.result(timeout=300)
            lat.append(time.perf_counter() - t0)
            futs.append(f)
        boxes[0].delay_s = 0.0
        stats = client.stats.snapshot()
        rec = {
            "kind": "latency_spike", "futures": len(futs),
            "policy": stats,
            "max_wait_s": round(max(lat), 3),
        }
        check(stats["hedges"] >= 1,
              "latency: hedged second dispatch fired")
        check(stats["hedge_wins"] >= 1,
              "latency: a hedge beat the slow replica")
        return rec

    # ------------------------------------------------------ 6: worker SIGKILL
    def inject_worker_sigkill():
        """kill -9 across the PROCESS boundary: the one fault class the
        in-process phases above cannot model (a thread cannot survive
        its own interpreter dying).  Self-contained router — the
        injection must not share fate with the in-process pool."""
        import signal

        from improved_body_parts_tpu.obs.fleet import verify_postmortem
        from improved_body_parts_tpu.serve.router import ProcessRouter

        t0 = time.perf_counter()
        small = np.zeros((48, 48, 3), np.uint8)
        router = ProcessRouter(
            "improved_body_parts_tpu.serve.worker:constant_predictor",
            num_workers=2,
            spec_kwargs={"num_parts": 18, "n_people": 2,
                         "delay_s": 0.2},
            slots=16, max_image_hw=(64, 64), num_parts=18,
            max_people=8, restart_after_s=0.3, probe_interval_s=0.05)
        with router:
            # path probe, and the proof the target worker is serving
            ledger.track(router.submit(small),
                         "worker_sigkill_probe").result(timeout=120)
            pid0 = router.workers[0].worker_stats()["pid"]
            futs = [ledger.track(router.submit(small), "worker_sigkill")
                    for _ in range(args.requests)]
            time.sleep(0.05)             # land the kill MID-batch
            os.kill(pid0, signal.SIGKILL)
            ok = err = 0
            for f in futs:
                try:
                    f.result(timeout=120)
                    ok += 1
                except Exception:  # noqa: BLE001 — typed = resolved
                    err += 1
            recovered_s = time.perf_counter() - t0
            respawned = wait_until(
                lambda: router.workers[0].restarts >= 2, timeout_s=30)
            pid1 = router.workers[0].worker_stats()["pid"]
            post = ledger.track(router.submit(small),
                                "worker_sigkill_post")
            # the process engine returns (people, signals) when the
            # escalation signal vector rides the response
            res = post.result(timeout=120)
            people = res[0] if isinstance(res, tuple) else res
            post_ok = isinstance(people, list) and len(people) > 0
            counters = router.counters()
            # the flight recorder's proof obligation: the exhumed ring
            # must IDENTIFY the killed batch (slot/seq + last completed
            # hop), not merely exist — verify_postmortem checks the
            # structure and that at least one in-flight request matched
            # a recorded milestone
            pm = router.workers[0].last_postmortem
            pm_ok, pm_problems = verify_postmortem(pm) \
                if pm is not None else (False, ["no postmortem exhumed"])
        rec = {
            "kind": "worker_sigkill",
            "in_flight_at_kill": len(futs),
            "resolved_ok": ok, "resolved_error": err,
            "killed_pid": pid0, "respawned_pid": pid1,
            "respawned": bool(respawned and pid1 != pid0),
            "worker_respawns": counters["worker_respawns"],
            "fenced": counters["fenced"],
            "failovers": counters["failovers"],
            "post_respawn_answered": post_ok,
            "recovery_s": round(recovered_s, 3),
            "postmortem_ok": pm_ok,
            "postmortem_problems": pm_problems,
            "postmortem_in_flight": (len(pm["in_flight"])
                                     if pm is not None else 0),
            "postmortem_last_hop": (pm["last_completed_hop"]
                                    if pm is not None else None),
        }
        check(ok + err == len(futs),
              "sigkill: every mid-batch future resolved")
        check(counters["fenced"] >= 1 and counters["failovers"] >= 1,
              "sigkill: pool fenced the dead worker and failed over")
        check(rec["respawned"],
              "sigkill: supervisor respawned a fresh process (new pid)")
        check(post_ok, "sigkill: respawned worker serves again")
        check(recovered_s < args.failover_bound,
              f"sigkill: recovery bounded ({recovered_s:.2f}s)")
        check(pm_ok, "sigkill: postmortem identifies the killed batch"
              + ("" if pm_ok else f" ({'; '.join(pm_problems)})"))
        return rec

    # --------------------------------------- 7: fastpath mid-skip-run
    def inject_fastpath_mid_skip_run():
        """The temporal-coherence fast path under the fault menu: a
        skipping stream is shed (drop_oldest), migrated mid-skip-run
        (parked real forwards re-submitted to a healthy replica, zero
        failures), and hard-stopped (the stranded real forward FAILS,
        the ``error`` escalation re-proves the scene, skipping
        resumes).  The three-tier conservation ledger must balance
        exactly through all of it."""
        t0 = time.perf_counter()
        fp = FastPathConfig(max_skip_run=2, min_stable=1)
        mgr = SessionManager(engines[0], fastpath=fp)
        sess_m = mgr.open("fp_migrate", max_in_flight=4, policy="block")
        sess_s = mgr.open("fp_shed", max_in_flight=2,
                          policy="drop_oldest")

        def drive(sess, n, wait=True, catch=False):
            futs = [ledger.track(sess.submit_frame(img),
                                 "fastpath_frame") for _ in range(n)]
            outcomes = []
            if wait:
                for f in futs:
                    try:
                        f.result(timeout=300)
                        outcomes.append("ok")
                    except Exception as e:  # noqa: BLE001 — typed
                        if not catch:
                            raise
                        outcomes.append(type(e).__name__)
            return futs, outcomes

        # phase a — prove skipping: sequential calm frames; with
        # max_skip_run=2 every 3rd frame is a real forward
        for _ in range(5):
            drive(sess_m, 1)
        skipped_before = sess_m.fastpath.metrics.answered_tracker
        # phase b — migration mid-skip-run: slow replica 0 so the next
        # owed real forward PARKS (any 3 consecutive frames contain
        # exactly one real), then rebind the stream to replica 1 — the
        # parked forward is re-submitted, nothing fails
        boxes[0].delay_s = 0.25
        futs_b, _ = drive(sess_m, 3, wait=False)
        time.sleep(0.05)      # let admissions land; the real forward
        moved = sess_m.migrate(engines[1])  # is parked in the delay
        for f in futs_b:
            f.result(timeout=300)
        boxes[0].delay_s = 0.0
        failed_after_migration = sess_m.fastpath.metrics.failed
        # phase c — hard-stop mid-skip-run: park the next real forward
        # on replica 1, then stop the replica with a drain too short to
        # finish it: the stranded frame fails with a typed error, the
        # fast path owes an ``error`` full forward
        boxes[1].delay_s = 0.3
        futs_c, _ = drive(sess_m, 3, wait=False)
        time.sleep(0.05)                  # land the stop mid-execute
        engines[1].stop(drain_timeout_s=0.05)
        outcomes_c = []
        for f in futs_c:
            try:
                f.result(timeout=300)
                outcomes_c.append("ok")
            except Exception as e:  # noqa: BLE001 — typed resolution
                outcomes_c.append(type(e).__name__)
        boxes[1].delay_s = 0.0
        # phase d — recovery: back on the live replica, the owed
        # ``error`` full forward re-proves the scene and skipping
        # resumes
        sess_m.migrate(engines[0])
        for _ in range(5):
            drive(sess_m, 1)
        snap_m = sess_m.fastpath.snapshot()
        # phase e — shed mid-skip-run on the second stream: establish
        # skipping, then burst a slowed replica at depth 2 under
        # drop_oldest — oldest frames shed as FrameDropped (a typed
        # resolution), accounted in the dropped bucket
        for _ in range(5):
            drive(sess_s, 1)
        boxes[0].delay_s = 0.25
        futs_e, outcomes_e = drive(sess_s, 9, catch=True)
        boxes[0].delay_s = 0.0
        snap_s = sess_s.fastpath.snapshot()
        mgr.close_all(timeout_s=60)
        recovered_s = time.perf_counter() - t0
        # replica 1 was hard-stopped: the pool fences it; restart it
        # (phase hygiene, same dance as the hard-stop phase)
        fenced = wait_until(
            lambda: pool.replica_states()[1]["state"] == "fenced",
            timeout_s=30)
        restarted = pool.restart(1)
        cons_m = {k: snap_m[k] for k in
                  ("submitted", "answered_tracker", "answered_roi",
                   "escalated_full", "failed", "dropped", "depth",
                   "exact")}
        cons_s = {k: snap_s[k] for k in cons_m}
        rec = {
            "kind": "fastpath_mid_skip_run",
            "migrate_stream": cons_m,
            "migrate_stream_escalations": snap_m["escalations"],
            "shed_stream": cons_s,
            "shed_stream_escalations": snap_s["escalations"],
            "skipped_before_faults": skipped_before,
            "frames_migrated": moved,
            "stop_outcomes": outcomes_c,
            "shed_outcomes": outcomes_e,
            "fenced": fenced, "restarted": restarted,
            "recovery_s": round(recovered_s, 3),
        }
        check(skipped_before >= 3,
              "fastpath: tracker tier engaged before the faults")
        check(moved >= 1,
              "fastpath: mid-skip-run migration re-submitted parked "
              "real forwards")
        check(failed_after_migration == 0,
              "fastpath: migration was invisible (zero failures)")
        check(snap_m["failed"] == 1,
              "fastpath: hard-stop stranded exactly the parked real "
              "forward")
        check(snap_m["escalations"]["error"] >= 1,
              "fastpath: the error escalation re-proved the scene")
        check(snap_m["answered_tracker"] > skipped_before,
              "fastpath: skipping resumed after recovery")
        check(snap_s["dropped"] >= 1,
              "fastpath: backpressure shed frames into the dropped "
              "bucket")
        check(cons_m["exact"] and cons_s["exact"],
              "fastpath: three-tier conservation exact through "
              "shed + migration + hard-stop")
        check(restarted, "fastpath: replica restarted into routing")
        return rec

    def ensure_all_live(after_kind):
        """Between-injection hygiene: only the TARGETED replica may
        have been fenced (and each phase restarts it); a healthy
        replica fenced by collateral (e.g. a false wedge verdict on a
        merely-busy replica) is a named failing check — and is
        restarted so one phase's fallout cannot cascade into the
        next phase's verdict."""
        stray = [s for s in pool.replica_states()
                 if s["state"] != "live"]
        check(not stray,
              f"{after_kind}: no collateral fences "
              f"({[(s['replica'], s['fence_reason']) for s in stray]})")
        for s in stray:
            pool.restart(s["replica"])

    for inject in (inject_wedged_fetcher, inject_poisoned_program,
                   inject_killed_decode_pool, inject_hard_stop_mid_stream,
                   inject_latency_spike, inject_worker_sigkill,
                   inject_fastpath_mid_skip_run):
        rec = inject()
        report["injections"].append(rec)
        ensure_all_live(rec["kind"])
        telemetry.emit("injection_done", kind=rec["kind"])
        print(f"injection {rec['kind']}: done", flush=True)

    # ----------------------------------------------------------- teardown
    # steady-state proof: after every injection + recovery, the pool
    # still serves clean traffic
    tail = [ledger.track(pool.submit(img), "steady_tail")
            for _ in range(n_rep)]
    for f in tail:
        f.result(timeout=300)
    pool.stop(drain_timeout_s=30.0)
    for b in boxes:
        b.release.set()                 # unpin anything still parked

    audit = ledger.audit()
    report["futures"] = audit
    check(audit["lost"] == 0,
          f"zero lost futures ({audit['tracked']} tracked, "
          f"{audit['resolved_ok']} ok, {audit['resolved_error']} typed "
          "errors)")

    # recompiles: the warm pool must have served the WHOLE sweep —
    # failovers, restarts, migrations — with zero new programs
    recompiles = int(telemetry.compile_watch.recompiles.value)
    report["recompiles_post_warmup"] = recompiles
    check(recompiles == 0, "0 post-warmup recompiles across the sweep")

    # thread leak scan: every serving thread must be gone (the wedged
    # fetcher was released above; timers cancelled; pools shut down)
    def leaked():
        return [t.name for t in threading.enumerate()
                if t.ident not in threads_before and t.is_alive()]

    wait_until(lambda: not leaked(), timeout_s=30)
    report["leaked_threads"] = leaked()
    check(not report["leaked_threads"],
          f"no leaked threads ({report['leaked_threads']})")
    def stdlib_singleton(pid):
        # multiprocessing's resource_tracker is a deliberate
        # process-wide singleton the stdlib keeps alive after the last
        # SharedMemory is unlinked — not a leak
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                return b"resource_tracker" in f.read()
        except OSError:
            return True      # reaped between the scan and the read

    def leaked_children():
        return sorted(pid for pid in set(proc_children())
                      - children_before if not stdlib_singleton(pid))

    wait_until(lambda: not leaked_children(), timeout_s=30)
    report["leaked_children"] = leaked_children()
    check(not report["leaked_children"], "no leaked descendants")

    report["pool_final"] = pool.snapshot()
    m = pool.metrics
    check(m.submitted == m.completed + m.failed + m.depth,
          "pool accounting conserved (submitted == completed + failed "
          "+ depth)")

    report["failed_checks"] = failures
    report["checks_failed"] = len(failures)
    report["ok"] = not failures
    telemetry.emit("chaos_serve_verdict", ok=report["ok"],
                   checks_failed=len(failures))
    telemetry.close()
    with open(args.out, "w") as f:
        strict_dump(report, f, indent=2)
    print(strict_dumps({
        "ok": report["ok"],
        "injections": [r["kind"] for r in report["injections"]],
        "futures_tracked": audit["tracked"],
        "futures_lost": audit["lost"],
        "recompiles_post_warmup": recompiles,
        "leaked_threads": len(report["leaked_threads"]),
        "checks_failed": len(failures)}))
    if args.strict and failures:
        sys.exit(1)


if __name__ == "__main__":
    main()
