#!/usr/bin/env python
"""Reconstruct per-request causal trees and verify causal completeness.

Input is a JSONL event stream containing ``request`` records (what
``obs.reqtrace.ReqTrace`` emits through the run's event sink — the
benches' ``*_events.jsonl`` files, or the stream a serving process
writes), or a JSON file with a top-level ``"records"`` list (the
``LATENCY_AUDIT.json`` shape).  For each request the record carries the
whole causal tree: one node per component that handled it (batcher,
pool, policy, cascade, stream), the edge kind that created each node
(submit / retry / hedge / failover / escalate / migrate) with its
reason annotation, and each node's hop waterfall.

The tool answers the two questions the raw records exist for:

- **Where did the slow requests' budgets go?**  ``--top N`` renders the
  N slowest requests as indented trees with their per-hop waterfalls —
  which hop ate the time is readable without a UI.
- **Is the tracing itself trustworthy?**  Causal completeness is
  verified over EVERY record: exactly one delivering leaf per request
  (the ``won_by`` chain from the root must exist, terminate, and end at
  a leaf — or at the interior node itself only when a client-side
  deadline resolved it), zero orphan nodes (every ``parent`` resolves
  inside the tree), zero duplicate node ids, zero duplicate request
  ids, and chain-hop conservation (the delivering chain's hop sum
  covers ``--min-coverage`` of the end-to-end span).  A tracing layer
  that drops or duplicates records under failover/hedge churn would
  read as a healthy system lying about its tail — these checks are what
  ``LATENCY_AUDIT.json`` gates on, including under the chaos arm's
  injected failovers.

    python tools/request_report.py SERVE_BENCH_events.jsonl --top 10
    python tools/request_report.py LATENCY_AUDIT.json --strict
"""
import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    read_events,
    strict_dump,
)

#: chain-hop conservation floor: the delivering chain's hop sum must
#: cover this fraction of the request's end-to-end span (the StepPhases
#: discipline applied per request)
MIN_COVERAGE = 0.95


def discover_shards(path):
    """Per-worker sink shards ``<path>.pN`` next to a primary stream
    (worker processes write their own shard so streams never
    interleave).  Globbed rather than probed consecutively from
    ``.p1`` — a crashed worker can leave a numbering hole that must
    not hide the surviving workers' shards."""
    shards = []
    for p in glob.glob(glob.escape(path) + ".p*"):
        suffix = p[len(path) + 2:]
        if suffix.isdigit():
            shards.append((int(suffix), p))
    return [p for _, p in sorted(shards)]


def load_records(path, shards=True):
    """``request`` records from a JSONL event stream or a JSON file
    with a top-level ``records`` list.

    For JSONL streams, per-worker sink shards (``<path>.pN``) are
    auto-discovered and their request records concatenated — unlike
    timing summaries, a request record carries its whole causal tree
    and durations in ms, so merging across processes is sound.  A shard
    whose ``run_start`` carries a ``run_id`` other than the primary
    stream's is a stale leftover from an earlier run: skipped loudly."""
    if path.endswith(".jsonl"):
        events = read_events(path)
        run_id = next((e.get("run_id") for e in reversed(events)
                       if e.get("event") == "run_start"), None)
        records = [e for e in events if e.get("event") == "request"]
        for sp in (discover_shards(path) if shards else []):
            sev = read_events(sp)
            srid = next((e.get("run_id") for e in reversed(sev)
                         if e.get("event") == "run_start"), None)
            if srid != run_id:
                print(f"{sp}: shard run_id {srid!r} does not match the "
                      f"primary stream's {run_id!r}; skipping stale "
                      "shard", file=sys.stderr)
                continue
            records.extend(e for e in sev
                           if e.get("event") == "request")
        return records
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        recs = data.get("records")
        if recs is None:
            raise SystemExit(
                f"{path}: no 'records' list — pass a JSONL event "
                "stream or a JSON file with a records list")
        return recs
    return data


def verify(records, min_coverage=MIN_COVERAGE):
    """Causal-completeness verdict over every record; returns the
    summary dict (``violations`` lists each failing request with the
    rule it broke)."""
    seen_req = set()
    out = {
        "requests": len(records),
        "duplicate_requests": 0,
        "duplicate_nodes": 0,
        "orphan_nodes": 0,
        "delivering_leaf_violations": 0,
        "coverage_violations": 0,
        "edge_kinds": {},
        "violations": [],
    }
    coverages = []
    for rec in records:
        req = rec.get("req")
        problems = []
        if req in seen_req:
            out["duplicate_requests"] += 1
            problems.append("duplicate request id")
        seen_req.add(req)
        nodes = rec.get("nodes", [])
        ids = [n.get("node") for n in nodes]
        by_id = {}
        for n in nodes:
            if n.get("node") in by_id:
                out["duplicate_nodes"] += 1
                problems.append(f"duplicate node id {n.get('node')}")
            by_id[n.get("node")] = n
            kind = n.get("kind", "?")
            out["edge_kinds"][kind] = out["edge_kinds"].get(kind, 0) + 1
        roots = [n for n in nodes if n.get("parent") is None]
        for n in nodes:
            if n.get("parent") is not None and \
                    n["parent"] not in by_id:
                out["orphan_nodes"] += 1
                problems.append(
                    f"orphan node {n.get('node')} (parent "
                    f"{n['parent']} missing)")
        # the delivering chain: follow won_by from the root
        children = {}
        for n in nodes:
            if n.get("parent") is not None:
                children.setdefault(n["parent"], []).append(n)
        chain_ok = len(roots) == 1
        if chain_ok:
            cur, hops_sum, steps = roots[0], 0.0, 0
            while True:
                hops_sum += sum(cur.get("hops_ms", {}).values())
                nxt = by_id.get(cur.get("won_by"))
                if cur.get("won_by") is not None and nxt is None:
                    chain_ok = False
                    problems.append(
                        f"won_by {cur['won_by']} not in tree")
                    break
                if nxt is None:
                    # chain terminus: must be a LEAF — exactly one
                    # delivering leaf — unless a client-side deadline
                    # resolved the request at an interior node (the
                    # only layer that can legally deliver without a
                    # child outcome)
                    is_leaf = not children.get(cur.get("node"))
                    deadline = "DeadlineExceeded" in str(
                        cur.get("status", ""))
                    if not is_leaf and not deadline:
                        chain_ok = False
                        problems.append(
                            f"chain ends at interior node "
                            f"{cur.get('node')} without a deadline")
                    break
                cur = nxt
                steps += 1
                if steps > len(nodes):
                    chain_ok = False
                    problems.append("won_by cycle")
                    break
            e2e = rec.get("e2e_ms", 0.0)
            if chain_ok and e2e > 0:
                cov = hops_sum / e2e
                coverages.append(cov)
                if cov < min_coverage:
                    out["coverage_violations"] += 1
                    problems.append(
                        f"chain hops cover {cov:.1%} of e2e "
                        f"(< {min_coverage:.0%})")
        else:
            problems.append(f"{len(roots)} roots (need exactly 1)")
        if not chain_ok:
            out["delivering_leaf_violations"] += 1
        if problems:
            out["violations"].append({"req": req, "problems": problems})
    if coverages:
        coverages.sort()
        out["chain_coverage"] = {
            "mean": round(sum(coverages) / len(coverages), 4),
            "p50": round(coverages[len(coverages) // 2], 4),
            "min": round(coverages[0], 4),
        }
    out["complete"] = not out["violations"]
    return out


def render_tree(rec):
    """One request as an indented causal tree with hop waterfalls."""
    nodes = rec.get("nodes", [])
    children = {}
    roots = []
    for n in nodes:
        if n.get("parent") is None:
            roots.append(n)
        else:
            children.setdefault(n["parent"], []).append(n)
    chain = set(rec.get("chain", []))
    lines = [f"req {rec.get('req')}  e2e {rec.get('e2e_ms')} ms  "
             f"status {rec.get('status')}  chain covers "
             f"{rec.get('hop_coverage', 0):.1%}"]

    def walk(n, depth):
        extras = {k: v for k, v in n.items()
                  if k not in ("node", "parent", "comp", "kind",
                               "t0_ms", "dur_ms", "status", "won_by",
                               "hops_ms")}
        hops = "  ".join(f"{h}={v}" for h, v in
                         n.get("hops_ms", {}).items())
        star = "*" if n.get("node") in chain else " "
        extra = ("  [" + " ".join(f"{k}={v}"
                                  for k, v in extras.items()) + "]"
                 if extras else "")
        lines.append(
            f"  {'  ' * depth}{star}{n.get('comp')}/{n.get('kind')}"
            f"  {n.get('dur_ms')} ms  ({n.get('status')}){extra}"
            + (f"\n  {'  ' * depth}   hops: {hops}" if hops else ""))
        for c in sorted(children.get(n.get("node"), []),
                        key=lambda x: x.get("t0_ms", 0)):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    return "\n".join(lines)


def slowest(records, top):
    return sorted(records, key=lambda r: -r.get("e2e_ms", 0.0))[:top]


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events", help="JSONL event stream with `request` "
                    "records, or a JSON file with a `records` list")
    ap.add_argument("--top", type=int, default=10,
                    help="render the N slowest request trees")
    ap.add_argument("--min-coverage", type=float, default=MIN_COVERAGE,
                    help="chain-hop conservation floor (fraction of "
                         "e2e the delivering chain must account for)")
    ap.add_argument("--json", default=None,
                    help="also write the verification summary + "
                         "slowest trees to this path")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on any completeness violation")
    ap.add_argument("--no-shards", action="store_true",
                    help="skip auto-discovery of <events>.pN worker "
                         "sink shards")
    args = ap.parse_args()

    records = load_records(args.events, shards=not args.no_shards)
    if not records:
        raise SystemExit(f"{args.events}: 0 request records — nothing "
                         "to report (was reqtrace enabled?)")
    summary = verify(records, args.min_coverage)
    slow = slowest(records, args.top)

    print(f"{summary['requests']} request records; "
          f"complete={summary['complete']} "
          f"(orphans={summary['orphan_nodes']}, "
          f"dup_nodes={summary['duplicate_nodes']}, "
          f"dup_reqs={summary['duplicate_requests']}, "
          f"leaf_violations={summary['delivering_leaf_violations']}, "
          f"coverage_violations={summary['coverage_violations']})")
    print("edge kinds: " + ", ".join(
        f"{k}={v}" for k, v in sorted(summary["edge_kinds"].items())))
    if "chain_coverage" in summary:
        cc = summary["chain_coverage"]
        print(f"chain coverage: mean {cc['mean']:.1%}  p50 "
              f"{cc['p50']:.1%}  min {cc['min']:.1%}")
    print(f"\nslowest {len(slow)} requests:")
    for rec in slow:
        print(render_tree(rec))
    for v in summary["violations"][:10]:
        print(f"VIOLATION req {v['req']}: {'; '.join(v['problems'])}",
              file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            strict_dump({"summary": summary, "slowest": slow}, f,
                        indent=2)
    if args.strict and not summary["complete"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
