#!/usr/bin/env python
"""graftaudit runner — compiled-program auditing over the registry.

    python tools/program_audit.py                  # full AOT sweep, gated
    python tools/program_audit.py --level trace    # jaxpr-only (fast)
    python tools/program_audit.py --bless          # record a new golden
    python tools/program_audit.py --programs train_step eval_step
    python tools/program_audit.py --format json    # machine-readable
    python tools/program_audit.py --rules          # the check table

Sweeps every program in ``analysis.program.registry`` abstractly
(``ShapeDtypeStruct``s + AOT ``.lower().compile()`` on the CPU backend
— zero real data, zero model FLOPs), runs the PRG checks, and compares
fingerprints against the committed ``PROGRAM_AUDIT.json`` golden
registry.  ``--bless`` rewrites the golden after an INTENTIONAL change
(a reviewed diff of the artifact is the blessing).

Exit codes: 0 = clean (no error findings, no drift); 1 = findings at
error severity or fingerprint drift; 2 = usage / internal error (a
crash must not read as "clean") — the graftlint contract.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the audit is SPECIFIED to run on the CPU backend (never claiming the
# exclusive TPU) with the virtual 8-device mesh the meshed programs
# need; both must land before the first jax import
os.environ["JAX_PLATFORMS"] = "cpu"
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()

GOLDEN_BASENAME = "PROGRAM_AUDIT.json"


def load_golden(path):
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="graftaudit: jaxpr/HLO-level checks + fingerprint "
                    "regression gating for every program the repo ships")
    ap.add_argument("--level", choices=("trace", "compile"),
                    default="compile",
                    help="trace = jaxpr checks only (~1 min); compile = "
                         "+ AOT compile per program (minutes, the full "
                         "donation/sharding/cost audit; default)")
    ap.add_argument("--programs", nargs="*", metavar="NAME",
                    help="restrict the sweep to these registry programs")
    ap.add_argument("--golden", default=os.path.join(REPO, GOLDEN_BASENAME),
                    help="golden registry path (default: committed "
                         f"{GOLDEN_BASENAME})")
    ap.add_argument("--bless", action="store_true",
                    help="write the audit result as the new golden "
                         "registry (full sweep only — a partial sweep "
                         "must not shrink the golden)")
    ap.add_argument("--out", default=None,
                    help="also write the full report JSON here")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rules", action="store_true",
                    help="print the check table and exit")
    args = ap.parse_args(argv)

    from improved_body_parts_tpu.analysis.program import (
        GRAFTAUDIT_VERSION,
        PROGRAM_RULES,
        audit_registry,
        audit_ruleset_hash,
        load_audit_config,
        program_registry,
    )

    if args.rules:
        for rule in PROGRAM_RULES:
            print(f"{rule.id}  {rule.name:20s} [{rule.severity}]  "
                  f"{rule.doc}")
        return 0

    known = {s.name for s in program_registry()}
    if args.programs is not None and not args.programs:
        # `--programs` with zero names must not read as "sweep nothing,
        # exit clean" — and `--bless --programs` would have replaced
        # the golden with an EMPTY registry
        print("program_audit: --programs requires at least one name; "
              f"registry has {sorted(known)}", file=sys.stderr)
        return 2
    if args.programs:
        unknown = sorted(set(args.programs) - known)
        if unknown:
            print(f"program_audit: unknown program(s) {unknown}; "
                  f"registry has {sorted(known)}", file=sys.stderr)
            return 2
        if args.bless:
            print("program_audit: --bless requires the FULL sweep (a "
                  "partial sweep must not shrink the golden registry)",
                  file=sys.stderr)
            return 2
    if args.bless and args.level != "compile":
        print("program_audit: --bless requires --level compile (a "
              "trace-only golden would silently drop the compiled "
              "fingerprints — donation aliases, cost analysis — from "
              "the gate)", file=sys.stderr)
        return 2

    config = load_audit_config(REPO)
    golden = None if args.bless else load_golden(args.golden)
    report = audit_registry(level=args.level, config=config, golden=golden,
                            names=args.programs)
    payload = report.as_dict()

    from improved_body_parts_tpu.obs.events import strict_dump, strict_dumps

    if args.bless:
        tmp = args.golden + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            strict_dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.golden)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            strict_dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.format == "json":
        print(strict_dumps(payload, indent=2, sort_keys=True))
    else:
        for v in report.verdicts:
            cfp = v.fingerprint.get("compiled") or {}
            tfp = v.fingerprint.get("trace") or {}
            cost = (f" flops={cfp.get('flops'):,}"
                    f" temp={cfp.get('temp_bytes'):,}"
                    f" alias={cfp.get('alias_bytes'):,}"
                    if cfp else f" eqns={tfp.get('eqn_count')}")
            print(f"{v.name:26s} {v.status:8s}{cost}"
                  + (f"  [{v.note}]" if v.note else ""))
            for f_ in v.findings:
                print(f"    {f_.format()}")
        counts = report.counts()
        drifted = sum(1 for v in report.verdicts if v.drift)
        gate = ("no golden registry — run with --bless to record one"
                if golden is None and not args.bless else
                f"golden jax {report.golden_jax_version or 'n/a'}, "
                f"{drifted} program(s) drifted")
        print(f"graftaudit {GRAFTAUDIT_VERSION} "
              f"(checks {audit_ruleset_hash()}): "
              f"{len(report.verdicts)} programs at level={args.level}, "
              f"{counts['error']} errors, {counts['warning']} warnings; "
              f"{gate}")
        if args.bless:
            print(f"blessed -> {args.golden}")

    return 0 if report.ok else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except KeyboardInterrupt:
        sys.exit(2)
