#!/usr/bin/env bash
# Download MS-COCO 2017 train/val/test images + keypoint annotations.
# Equivalent of the reference's data/dataset/get_dataset.sh (gsutil), with
# a curl fallback for hosts without the gcloud SDK.
#
#   tools/get_dataset.sh [target_dir]   # default: ./data/coco2017
#
# Afterwards build the training corpus:
#   python tools/make_corpus.py --anno <dir>/annotations/person_keypoints_train2017.json \
#       --images <dir>/train2017 --out coco_train.h5
set -euo pipefail

DIR="${1:-./data/coco2017}"
mkdir -p "$DIR"
cd "$DIR"

fetch() {
    # extract into a temp dir and mv into place so an interrupted unzip
    # can never masquerade as a complete dataset on rerun
    local url="$1" name
    name="$(basename "$url")"
    local out="${2:-${name%.zip}}"
    if [ -e "$out" ]; then
        echo "$out already present, skipping"
        return
    fi
    if [ ! -f "$name" ]; then
        # download to .part and mv into place so an interrupted download
        # can never masquerade as a complete zip on rerun (-C - resumes;
        # a gsutil partial is deleted first — its sliced writes are not
        # prefix-consistent, so resuming on top of one would corrupt)
        if command -v gsutil >/dev/null 2>&1 && [[ "$url" == *images.cocodataset.org/zips/* ]]; then
            gsutil -m cp "gs://images.cocodataset.org/zips/${name}" "$name.part" 2>/dev/null \
                || { rm -f "$name.part"; curl -fL -C - -o "$name.part" "$url"; }
        else
            curl -fL -C - -o "$name.part" "$url"
        fi
        mv "$name.part" "$name"
    fi
    # fixed temp name (not $$): a failed run's leftovers are removed by
    # the rerun instead of accumulating under fresh PID names
    local tmp=".extract_${name%.zip}"
    rm -rf "$tmp" && mkdir "$tmp"
    if ! unzip -q "$name" -d "$tmp"; then
        rm -rf "$tmp" "$name"
        echo "unzip failed for $name — deleted it; rerun to re-download" >&2
        exit 1
    fi
    # rm -rf, not rmdir: stray zip cruft (e.g. __MACOSX/) must not fail
    # an otherwise-successful extraction after the data was moved
    mv "$tmp/$out" .
    rm -rf "$tmp" "$name"
}

fetch "http://images.cocodataset.org/zips/train2017.zip"
fetch "http://images.cocodataset.org/zips/val2017.zip"
fetch "http://images.cocodataset.org/zips/test2017.zip"
fetch "http://images.cocodataset.org/annotations/annotations_trainval2017.zip" annotations

echo "COCO 2017 ready under $DIR"
