#!/usr/bin/env python
"""End-to-end request-path latency audit → LATENCY_AUDIT.json.

The proof artifact for the request-tracing + SLO layer: every claim the
obs/reqtrace waterfall makes about where a request's budget goes is
checked against ground it can't fake, on REAL warm serve pipelines
(jitted programs, the standard warmup path, a compile watch over the
whole sweep).  Four arms:

1. **plain serve** — closed-loop clients against one warm
   ``DynamicBatcher``; gates that the five-hop decomposition
   (queue / batch_formation / device / decode / deliver,
   ``serve.metrics.HOPS``) sums to ≥95% of measured e2e latency — both
   at the registry level (hop reservoir sums vs the latency reservoir
   sum) and per request (the delivering chain's hop coverage).  An
   ``obs.slo.SLOTracker`` rides this arm and its state lands in the
   artifact (the ``/slo`` consumable).
2. **cascade** — the same gates across a student→teacher
   ``CascadeEngine`` on a mixed easy/hard stream (the tiered planted
   shim from ``tools/cascade_bench.py``): escalated requests must keep
   chain conservation through the ESCALATE hop edge (the
   ``student_lane`` gap hop is what makes that possible).
3. **chaos** — a 2-replica ``EnginePool`` behind a hedging
   ``PolicyClient``; mid-traffic one replica is hard-stopped out from
   under the pool (the SERVE_CHAOS injection class).  Gates causal
   completeness where it is hardest: every record a complete tree with
   exactly one delivering leaf, zero orphan/duplicate records, and the
   sweep must actually have exercised ``failover`` and ``hedge`` edges
   (a chaos arm that injected nothing proves nothing).
4. **overhead** — the serve-path reqtrace A/B
   (``tools/telemetry_overhead.serve_overhead_ab``, the
   TELEMETRY_OVERHEAD estimator): the full tracing stack must cost <2%.

Plus: slowest-10 request trees (via ``tools/request_report``), and 0
post-warmup recompiles across every arm — tracing must add no jitted
programs.

    python tools/latency_audit.py --out LATENCY_AUDIT.json
    python tools/latency_audit.py --quick     # bench.py's "slo" smoke
"""
import argparse
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

CONSERVATION_FLOOR = 0.95


def run_clients(n_clients, requests, work_fn):
    errors = []

    def client(cid):
        try:
            for i in range(requests):
                work_fn(cid, i)
        except Exception as e:  # noqa: BLE001 — surfaced after join
            errors.append(e)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return wall


def drain_records(reqtrace, expected, timeout_s=30.0):
    """Request records assemble when the LAST node of each tree
    finishes — a losing hedge/failover attempt can land after the
    caller's future resolved.  Wait for the in-flight table to drain."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        recs = reqtrace.records()
        if len(recs) >= expected and reqtrace.live == 0:
            return recs
        time.sleep(0.02)
    return reqtrace.records()


def arm_summary(records, snapshot, verify):
    """The per-arm artifact block: registry-level hop decomposition +
    per-request chain conservation + causal completeness."""
    hops = snapshot["hops_ms"]
    summary = verify(records)
    covs = sorted(r["hop_coverage"] for r in records)
    return {
        "requests": len(records),
        "e2e_ms": snapshot["latency_ms"],
        "hops_ms": hops,
        "registry_conservation_frac": snapshot["hop_conservation_frac"],
        "chain_coverage_p50": (covs[len(covs) // 2] if covs else None),
        "chain_coverage_min": (covs[0] if covs else None),
        "causal": {k: summary[k] for k in
                   ("complete", "orphan_nodes", "duplicate_nodes",
                    "duplicate_requests", "delivering_leaf_violations",
                    "coverage_violations", "edge_kinds")},
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--size", type=int, default=192,
                    help="square frame size (also boxsize: one bucket)")
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--requests", type=int, default=10,
                    help="closed-loop requests per client per arm")
    ap.add_argument("--overhead-rounds", type=int, default=6)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=15.0)
    ap.add_argument("--quick", action="store_true",
                    help="bench.py smoke shape: fewer clients/requests/"
                         "rounds, smaller frames")
    ap.add_argument("--out", default="LATENCY_AUDIT.json")
    args = ap.parse_args()
    if args.quick:
        args.size = min(args.size, 128)
        args.clients = 2
        args.requests = 6
        args.overhead_rounds = 4

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import numpy as np

    platform = devices_with_timeout(900)[0].platform
    print(f"platform={platform}", flush=True)

    import jax.numpy as jnp

    from cascade_bench import TieredPlantedModel, make_images, plant_people
    from chaos_serve import ChaosBox, ChaosPredictor
    from e2e_bench import PlantedModel, planted_maps, synth_images
    from request_report import slowest, verify
    from telemetry_overhead import serve_overhead_ab

    from improved_body_parts_tpu.config import (
        InferenceModelParams, get_config)
    from improved_body_parts_tpu.infer.predict import Predictor
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs import (
        Objective, Registry, RunTelemetry, SLOTracker)
    from improved_body_parts_tpu.serve import (
        CascadeEngine, DynamicBatcher, EnginePool, EscalationPolicy,
        PolicyClient, ServeMetrics, submit_with_retry)

    size = args.size
    rng = np.random.default_rng(0)
    sizes = [(size, size)]
    batcher_kw = dict(max_batch=args.max_batch,
                      max_wait_ms=args.max_wait_ms, max_queue=64)

    def make_pred(cfg_name, model_wrap):
        cfg = get_config(cfg_name)
        model = build_model(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, size, size, 3)),
                               train=False)
        return Predictor(model_wrap(model, cfg), variables,
                         cfg.skeleton,
                         model_params=InferenceModelParams(
                             boxsize=size, max_downsample=64),
                         bucket=64)

    tiny_sk = get_config("tiny").skeleton
    plain_pred = make_pred(
        "tiny", lambda m, cfg: PlantedModel(
            m, planted_maps(cfg.skeleton, 2, rng,
                            canvas=max(size * 2, 256)), cfg.skeleton))
    images = synth_images(4, size, rng)

    # the SLO layer rides the plain arm; its state lands in the
    # artifact as the /slo consumable
    slo = SLOTracker([Objective("interactive", latency_ms=5000.0,
                                target=0.99, windows_s=(30.0, 120.0))])
    sink_path = os.path.splitext(args.out)[0] + "_events.jsonl"
    if os.path.exists(sink_path):
        os.unlink(sink_path)
    telemetry = RunTelemetry(
        sink_path, registry=Registry(), reqtrace_sample=1, slo=slo,
        run_meta={"tool": "latency_audit", "platform": platform})
    rt = telemetry.reqtrace

    report = {
        "platform": platform,
        "size": size, "clients": args.clients,
        "requests_per_client": args.requests,
        "conservation_floor": CONSERVATION_FLOOR,
        "telemetry_events": sink_path,
        "note": "All arms run real jitted serve programs behind "
                "planted-map shims (the standing bench discipline); "
                "per-hop sums are checked against the e2e reservoir "
                "at the registry level AND per request along the "
                "delivering chain. CPU-host absolute numbers are not "
                "the claim — conservation, causal completeness and "
                "the overhead ratio are.",
    }

    def closed_loop(submit):
        def work(cid, i):
            img = images[(cid + i * args.clients) % len(images)]
            fut, _ = submit_with_retry(submit, img, base_s=0.002,
                                       max_s=0.05)
            fut.result(timeout=300)
        return run_clients(args.clients, args.requests, work)

    # per-arm recompile accounting: each arm fences AFTER its own
    # warmup and reads the process compile counter's delta over its
    # traffic — a multi-arm audit cannot use one global mark_warm (each
    # later arm's legitimate warmup would count against the earlier
    # fence)
    def compiles():
        return int(telemetry.compile_watch.compiles.value)

    arm_recompiles = {}

    # --- arm 1: plain serve ------------------------------------------
    n_arm = args.clients * args.requests
    server = DynamicBatcher(plain_pred, registry=telemetry.registry,
                            slo=slo, qos_class="interactive",
                            **batcher_kw)
    with server:
        server.warmup(sizes)
        c0 = compiles()
        closed_loop(server.submit)
        arm_recompiles["plain_serve"] = compiles() - c0
        recs = drain_records(rt, n_arm)
        report["plain_serve"] = arm_summary(
            recs, server.metrics.snapshot(), verify)
    report["slo"] = slo.state()
    print(f"plain serve: conservation "
          f"{report['plain_serve']['registry_conservation_frac']} "
          f"chain p50 {report['plain_serve']['chain_coverage_p50']}",
          flush=True)

    # --- arm 2: cascade ----------------------------------------------
    easy_maps, _ = plant_people(tiny_sk, 2, rng, size)
    hard_maps, _ = plant_people(tiny_sk, 6, rng, size)
    student = make_pred("tiny_student", lambda m, cfg: TieredPlantedModel(
        m, easy_maps, hard_maps, cfg.skeleton))
    teacher = make_pred("tiny", lambda m, cfg: TieredPlantedModel(
        m, easy_maps, hard_maps, cfg.skeleton))
    easy_imgs, hard_imgs = make_images(size, 3, rng)
    # every 4th frame hard: the escalate edge must appear in records
    mixed = [hard_imgs[i // 4 % len(hard_imgs)] if i % 4 == 3
             else easy_imgs[i % len(easy_imgs)] for i in range(8)]
    base = len(rt.records())
    cascade = CascadeEngine.build(
        student, teacher, policy=EscalationPolicy(max_people=4),
        registry=telemetry.registry, **batcher_kw)
    with cascade:
        cascade.warmup(sizes)
        c0 = compiles()
        images_save, images[:] = images[:], mixed
        try:
            closed_loop(cascade.submit)
        finally:
            images[:] = images_save
        arm_recompiles["cascade"] = compiles() - c0
        recs = drain_records(rt, base + n_arm)[base:]
        report["cascade"] = arm_summary(
            recs, cascade.student.metrics.snapshot(), verify)
        report["cascade"]["routing"] = cascade.metrics.snapshot()
    esc_edges = report["cascade"]["causal"]["edge_kinds"].get(
        "escalate", 0)
    print(f"cascade: chain p50 "
          f"{report['cascade']['chain_coverage_p50']} "
          f"escalate edges {esc_edges}", flush=True)

    # --- arm 3: chaos (failover + hedge) -----------------------------
    # the SERVE_CHAOS injection machinery: shared-nothing replicas
    # (one Predictor per engine — never two dispatchers on one program
    # cache), replica 0 wrapped in a ChaosBox whose POISON makes its
    # next N resolves raise mid-execute — a deterministic failover
    # source (every poisoned batch's requests fail over to replica 1)
    boxes = [ChaosBox(), ChaosBox()]
    chaos_preds = [
        ChaosPredictor(make_pred(
            "tiny", lambda m, cfg: PlantedModel(
                m, planted_maps(cfg.skeleton, 2, rng,
                                canvas=max(size * 2, 256)),
                cfg.skeleton)), boxes[i])
        for i in range(2)]
    engines = [DynamicBatcher(chaos_preds[i], metrics=ServeMetrics(),
                              **batcher_kw) for i in range(2)]
    base = len(rt.records())
    pool = EnginePool(engines, probe_interval_s=0.05,
                      wedge_timeout_s=30.0, drain_timeout_s=5.0,
                      fence_on_breaker=False,
                      registry=telemetry.registry)
    with pool:
        pool.warmup(sizes)
        # hedge fires at ~half a typical request's latency: most
        # requests dispatch a covering attempt, some hedges win
        warm_t0 = time.perf_counter()
        pool.submit(images[0]).result(timeout=300)
        typical = time.perf_counter() - warm_t0
        c0 = compiles()
        client = PolicyClient(pool, hedge_after_s=max(typical * 0.5,
                                                      0.005),
                              max_attempts=8)
        n_poison = max(2, n_arm // 4)
        boxes[0].poison_left = n_poison

        def chaos_work(cid, i):
            img = images[(cid + i * args.clients) % len(images)]
            client.submit(img).result(timeout=300)

        run_clients(args.clients, args.requests, chaos_work)
        arm_recompiles["chaos"] = compiles() - c0
        recs = drain_records(rt, base + n_arm + 1)[base:]
    chaos_verify = verify(recs)
    kinds = chaos_verify["edge_kinds"]
    report["chaos"] = {
        "requests": len(recs),
        "injection": f"replica 0 poisoned for {n_poison} resolves "
                     f"(mid-execute raise -> failover) + hedging "
                     f"policy client",
        "policy": client.stats.snapshot(),
        "pool_counters": pool.counters(),
        "causal": {k: chaos_verify[k] for k in
                   ("complete", "orphan_nodes", "duplicate_nodes",
                    "duplicate_requests", "delivering_leaf_violations",
                    "coverage_violations", "edge_kinds")},
        "failover_edges": kinds.get("failover", 0),
        "hedge_edges": kinds.get("hedge", 0),
    }
    print(f"chaos: {len(recs)} records, failover edges "
          f"{report['chaos']['failover_edges']}, hedge edges "
          f"{report['chaos']['hedge_edges']}, complete "
          f"{chaos_verify['complete']}", flush=True)

    # --- slowest request trees (across every arm's records) ----------
    report["slowest_requests"] = slowest(rt.records(), 10)
    # the committed events stream must survive the standalone verifier
    # (`request_report --strict`) — every record of every arm
    all_verify = verify(rt.records())
    report["all_records"] = {
        "requests": all_verify["requests"],
        "complete": all_verify["complete"],
        "violations": len(all_verify["violations"]),
    }

    # --- arm 4: serve-path overhead A/B ------------------------------
    oh_c0 = [None]
    report["reqtrace_overhead"] = serve_overhead_ab(
        plain_pred, sizes, images, 2, max(4, args.requests // 2),
        args.overhead_rounds, batcher_kw=batcher_kw,
        on_warm=lambda: oh_c0.__setitem__(0, compiles()))
    arm_recompiles["overhead_ab"] = compiles() - oh_c0[0]
    print(f"overhead: {report['reqtrace_overhead']['overhead_pct']}% "
          f"(budget {report['reqtrace_overhead']['budget_pct']}%)",
          flush=True)

    report["recompiles_by_arm"] = arm_recompiles
    report["recompiles_post_warmup"] = int(sum(arm_recompiles.values()))
    slow_verify = verify(report["slowest_requests"])
    report["gates"] = {
        "plain_conservation_ge_95": bool(
            report["plain_serve"]["registry_conservation_frac"]
            >= CONSERVATION_FLOOR
            and report["plain_serve"]["chain_coverage_p50"]
            >= CONSERVATION_FLOOR),
        "cascade_conservation_ge_95": bool(
            report["cascade"]["chain_coverage_p50"]
            >= CONSERVATION_FLOOR),
        "slowest_trees_complete": bool(slow_verify["complete"]),
        "chaos_zero_orphans_dupes": bool(
            chaos_verify["orphan_nodes"] == 0
            and chaos_verify["duplicate_nodes"] == 0
            and chaos_verify["duplicate_requests"] == 0),
        "chaos_trees_complete": bool(chaos_verify["complete"]),
        "chaos_exercised_failover_and_hedge": bool(
            report["chaos"]["failover_edges"] > 0
            and report["chaos"]["hedge_edges"] > 0),
        "overhead_within_budget": bool(
            report["reqtrace_overhead"]["within_budget"]),
        "zero_post_warmup_recompiles": bool(
            report["recompiles_post_warmup"] == 0),
        "all_records_complete": bool(report["all_records"]["complete"]),
    }
    report["gates"]["all"] = all(report["gates"].values())

    telemetry.close()
    with open(args.out, "w") as f:
        strict_dump(report, f, indent=2)
    print(strict_dumps({"gates": report["gates"]}))
    if not report["gates"]["all"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
