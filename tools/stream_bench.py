#!/usr/bin/env python
"""Multi-stream closed-loop streaming benchmark: N simulated webcams
through ONE serving engine, per-stream FPS and end-to-end latency.

Each simulated webcam is a deterministic synthetic video
(``stream.SyntheticVideo`` — moving planted stick people) driven
closed-loop through its own ``StreamSession`` (``stream.session``): the
client submits frames as fast as the session admits them, the session's
``max_in_flight`` bound pipelines the stream against the engine, and
results (tracked people) deliver strictly in frame order.  This is the
first genuinely concurrent, stateful workload the stack carries — it
exercises the batcher with sustained heterogeneous traffic and the
tracker/smoother with real per-stream sequential state.

Verdict protocol (the standing ROADMAP bench discipline): rounds
interleave an N-stream arm and a 1-stream arm, so slow host drift hits
both arms of a round equally; the reported scaling ratio is the median
per-round ``aggregate_multi_fps / single_stream_fps``.  Post-warmup
recompiles are counted by the obs CompileWatch and must be 0.

Writes STREAM_BENCH.json: per-stream FPS, per-stream p50/p95 e2e
latency, dropped-frame and track-churn accounting, the scaling verdict
and the recompile count.

    python tools/stream_bench.py --config tiny --streams 4 --frames 16 \
        --size 128 --boxsize 128 --out STREAM_BENCH.json
"""
import argparse
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


def run_streams(manager, videos, frames, policy, max_in_flight=None,
                fastpath=None):
    """Drive one closed-loop slice: each video gets its own session +
    client thread; returns (wall_s, per-session snapshots in stream
    order, id-stability flags).  ``max_in_flight=1`` is the serial
    baseline (submit → wait → next, no pipelining); ``fastpath`` is a
    ``FastPathConfig`` for the temporal-coherence arm (None = every
    frame pays a full forward)."""
    from improved_body_parts_tpu.stream import FrameDropped

    sessions = [manager.open(f"cam{i}", policy=policy,
                             max_in_flight=max_in_flight,
                             fastpath=fastpath)
                for i in range(len(videos))]
    stable = [True] * len(videos)
    errors = []

    def client(ci):
        vid = videos[ci]
        session = sessions[ci]
        futs = []
        try:
            for t in range(frames):
                # closed loop bounded by the session's in-flight depth:
                # submit as fast as admission allows, the session blocks
                # (or drops) at max_in_flight
                futs.append(session.submit_frame(vid.frame(t % len(vid))))
            first_ids = None
            for fut in futs:
                try:
                    tracked = fut.result(timeout=600)
                except FrameDropped:
                    continue        # accounted by the session metrics
                ids = sorted(p.track_id for p in tracked)
                if first_ids is None:
                    first_ids = ids
                elif ids != first_ids:
                    stable[ci] = False
        except Exception as e:  # noqa: BLE001 — surfaced after join
            errors.append(e)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(len(videos))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    snaps = [s.snapshot() for s in sessions]
    for s in sessions:
        s.close(timeout_s=60)
    if errors:
        raise errors[0]
    return wall, snaps, stable


def arm_summary(wall, snaps, stable):
    delivered = sum(s["frames_delivered"] for s in snaps)
    return {
        "streams": len(snaps),
        "wall_s": round(wall, 3),
        "aggregate_fps": round(delivered / wall, 3) if wall else 0.0,
        "per_stream_fps": [s["fps"] for s in snaps],
        "per_stream_p50_ms": [s["e2e_latency_ms"]["p50"] for s in snaps],
        "per_stream_p95_ms": [s["e2e_latency_ms"]["p95"] for s in snaps],
        "frames_delivered": delivered,
        "frames_dropped": sum(s["frames_dropped"] for s in snaps),
        "frames_failed": sum(s["frames_failed"] for s in snaps),
        # engine-admission sheds absorbed by the sessions' jittered
        # backoff (serve.policy) — reported, never counted as failures
        "engine_shed_retries": sum(s["engine_shed_retries"]
                                   for s in snaps),
        "track_births": sum(s["tracker"]["births"] for s in snaps),
        "track_deaths": sum(s["tracker"]["deaths"] for s in snaps),
        "track_ids_stable": all(stable),
    }


# --------------------------------------------------------------- fastpath
def fastpath_block(snaps):
    """Aggregate the per-stream three-tier accounting into the artifact's
    per-tier conservation block + per-tier p50/p95/p99 latency block.

    Counters sum exactly across streams; ``exact`` holds iff every
    stream's own invariant held AND the summed ledger balances.  The
    percentile block reports, per tier and quantile, the MEDIAN across
    streams of that stream's quantile (reservoirs cannot be merged;
    the median-of-streams view is drift-robust the same way the round
    protocol is)."""
    import numpy as np

    fps = [s["fastpath"] for s in snaps]
    keys = ("submitted", "answered_tracker", "answered_roi",
            "escalated_full", "failed", "dropped", "depth")
    conservation = {k: sum(f[k] for f in fps) for k in keys}
    conservation["exact"] = (
        all(f["exact"] for f in fps)
        and conservation["submitted"]
        == sum(conservation[k] for k in keys[1:]))
    escalations = {}
    for f in fps:
        for reason, n in f["escalations"].items():
            escalations[reason] = escalations.get(reason, 0) + n
    tier_latency = {}
    for tier in ("tracker", "roi", "full"):
        answered = [f["tier_latency_ms"][tier] for f in fps
                    if f["tier_latency_ms"][tier]["count"] > 0]
        if not answered:
            continue
        tier_latency[tier] = {
            "count": sum(t["count"] for t in answered),
            **{q: round(float(np.median([t[q] for t in answered])), 3)
               for q in ("p50", "p95", "p99")}}
    submitted = max(conservation["submitted"], 1)
    return {
        "conservation": conservation,
        "escalations": escalations,
        "tier_latency_ms": tier_latency,
        "skip_rate": round(conservation["answered_tracker"] / submitted, 4),
        "roi_rate": round(conservation["answered_roi"] / submitted, 4),
    }


#: COCO-style OKS thresholds for the synthetic-AP quality gate
OKS_THRESHOLDS = tuple(round(0.5 + 0.05 * i, 2) for i in range(10))


class SyntheticAP:
    """OKS-matched average precision against the generator's ground
    truth: per frame, GT people greedily match delivered people on the
    same OKS similarity the tracker uses; per threshold t,
    ``AP_t = matches(OKS >= t) / max(n_gt, n_delivered)`` summed over
    frames, and the reported AP is the mean over the COCO threshold
    ladder.  An arm that delivers exactly the GT scores 1.0 — which is
    what the noise-free quality protocol demands from BOTH arms."""

    def __init__(self):
        self.tp = {t: 0 for t in OKS_THRESHOLDS}
        self.denom = 0

    def update(self, gt_people, tracked):
        import numpy as np

        from improved_body_parts_tpu.stream.track import (
            _extent_area, _to_arrays, greedy_match, keypoint_similarity)

        refs = [_to_arrays(coords) for _, coords in gt_people]
        dets = [_to_arrays(p.keypoints) for p in tracked]
        sim = np.zeros((len(refs), len(dets)), dtype=np.float64)
        for gi, (gxy, gvalid) in enumerate(refs):
            area = _extent_area(gxy, gvalid)
            for di, (dxy, dvalid) in enumerate(dets):
                sim[gi, di] = keypoint_similarity(
                    gxy, gvalid, dxy, dvalid, area=area)
        matched = [sim[gi, di] for gi, di in greedy_match(sim, 1e-6)]
        for t in OKS_THRESHOLDS:
            self.tp[t] += sum(1 for s in matched if s >= t)
        self.denom += max(len(refs), len(dets))

    def value(self):
        if self.denom == 0:
            return 0.0
        return float(sum(self.tp[t] / self.denom
                         for t in OKS_THRESHOLDS)) / len(OKS_THRESHOLDS)


def quality_arm(scene, frames, size, people, seed, fp_cfg):
    """One deterministic quality protocol run: a stamped-frame
    ``SyntheticVideo`` scene driven through a ``StreamSession`` over the
    ground-truth ``DetectionEngine`` (no model, no device — the engine
    answers crops honestly, windowed to what the crop can see).
    Returns synthetic-AP, IDSW, engine forwards, and — fastpath arms —
    the tier mix + conservation, so the A/B can gate EQUAL quality at a
    fraction of the forwards."""
    from improved_body_parts_tpu.stream import (
        DetectionEngine, IdentitySwitchCounter, SessionManager,
        SyntheticVideo)

    vid = SyntheticVideo(seed=seed, num_people=people, size=(size, size),
                         num_frames=frames, scene=scene)
    eng = DetectionEngine(vid)
    manager = SessionManager(eng, smoothing=None, max_in_flight=1)
    session = manager.open(f"q_{scene}", fastpath=fp_cfg)
    ap = SyntheticAP()
    idsw = IdentitySwitchCounter()
    for t in range(frames):
        tracked = session.submit_frame(vid.stamped_frame(t)).result(
            timeout=120)
        gt = vid.gt(t)
        ap.update(gt, tracked)
        idsw.update(gt, tracked)
    snap = session.snapshot()
    manager.close_all(timeout_s=60)
    out = {
        "frames": frames,
        "synthetic_ap": round(ap.value(), 6),
        "identity_switches": idsw.switches,
        "engine_forwards": eng.calls,
    }
    if fp_cfg is not None:
        out["fastpath"] = fastpath_block([snap])
    return out


class _Video:
    """Pre-rendered frame cycle for one simulated webcam (rendering is
    cv2 host work; pre-rendering keeps the measured loop pure
    submit/deliver)."""

    def __init__(self, vid):
        self._frames = vid.frames()

    def __len__(self):
        return len(self._frames)

    def frame(self, t):
        return self._frames[t]


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--streams", type=int, default=4,
                    help="concurrent simulated webcams in the multi arm")
    ap.add_argument("--frames", type=int, default=24,
                    help="frames each stream submits per round")
    ap.add_argument("--video-frames", type=int, default=16,
                    help="distinct frames per synthetic video (cycled)")
    ap.add_argument("--people", type=int, default=2,
                    help="moving stick people per stream")
    ap.add_argument("--size", type=int, default=256,
                    help="square frame size of the simulated webcams")
    ap.add_argument("--rounds", type=int, default=3,
                    help="interleaved multi/single verdict rounds")
    ap.add_argument("--policy", default="block",
                    choices=["block", "drop_oldest"])
    ap.add_argument("--max-in-flight", type=int, default=4,
                    help="per-stream pipeline depth (the backpressure "
                         "bound)")
    ap.add_argument("--smoothing", default="one_euro",
                    choices=["none", "one_euro", "ema"])
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-wait-ms", type=float, default=25.0)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--decode-workers", type=int, default=2)
    ap.add_argument("--boxsize", type=int, default=0,
                    help="override InferenceModelParams.boxsize (0 = "
                         "default protocol); set to the frame size to "
                         "keep CPU smoke runs small")
    ap.add_argument("--planted", type=int, default=2,
                    help="plant GT-style maps for N synthetic people "
                         "(realistic decode workload, as serve_bench; "
                         "the maps are static, so the tracker sees a "
                         "steady crowd)")
    ap.add_argument("--planted-canvas", type=int, default=0,
                    help="canvas px the planted crowd is laid out on "
                         "(0 = auto).  Planting is content-blind, so "
                         "the crowd's extent is set by the canvas, not "
                         "the frame: a canvas equal to the frame size "
                         "hugs the crowd into the top-left, which lets "
                         "the fastpath ROI window anchor at x0=0 — "
                         "there a width-crop decodes EXACTLY like the "
                         "full frame (same planted map region, no "
                         "offset), so the ROI tier runs honestly over "
                         "the planted model")
    ap.add_argument("--fastpath", action="store_true",
                    help="temporal-coherence A/B: rounds interleave a "
                         "fastpath-on and a fastpath-off N-stream arm "
                         "over the same engine (instead of the multi/"
                         "single scaling protocol), with per-arm "
                         "compile-delta accounting, the three-tier "
                         "conservation block, and the deterministic "
                         "quality protocols (static + slow_pan scenes "
                         "over the ground-truth engine) gating EQUAL "
                         "synthetic-AP and IDSW")
    ap.add_argument("--fp-max-skip-run", type=int, default=3,
                    help="consecutive tracker-tier answers before a "
                         "real forward is owed")
    ap.add_argument("--fp-min-stable", type=int, default=2,
                    help="calm real deliveries before skipping starts")
    ap.add_argument("--fp-roi-width", type=int, default=0,
                    help="ROI crop width in px — the ONE extra warmup "
                         "bucket (size, roi_width); 0 disables the ROI "
                         "tier")
    ap.add_argument("--fp-roi-margin", type=int, default=32,
                    help="padding around the union track box before "
                         "the ROI fit check")
    ap.add_argument("--fp-full-refresh-every", type=int, default=4,
                    help="every Nth real forward is full-frame even "
                         "when the box fits the ROI window")
    ap.add_argument("--fp-people-delta", type=int, default=0,
                    help="tolerated |person-count delta| before a full "
                         "forward is owed.  Raise it in the throughput "
                         "arm when serving a PLANTED model: planting is "
                         "content-blind, so a narrower crop decodes a "
                         "different person count than the full frame — "
                         "an artifact of the fake model, not the scene "
                         "(the quality arms run an honest ground-truth "
                         "engine at people_delta=0)")
    ap.add_argument("--fp-gate", type=float, default=3.0,
                    help="sustained-streams multiplier the fastpath-on "
                         "arm must reach (median per-round aggregate-"
                         "fps ratio vs the fastpath-off arm)")
    ap.add_argument("--fp-quality-frames", type=int, default=48,
                    help="frames per deterministic quality protocol "
                         "scene")
    ap.add_argument("--params-dtype", default="auto",
                    choices=["auto", "bf16", "fp32"])
    ap.add_argument("--no-native", action="store_true")
    ap.add_argument("--devices", type=int, default=0,
                    help="device replicas the batcher serves across "
                         "(0 = all visible devices)")
    ap.add_argument("--telemetry-sink", default="auto",
                    help="JSONL event stream ('auto' = <out>_events"
                         ".jsonl, 'none' disables)")
    ap.add_argument("--telemetry-port", type=int, default=-1)
    ap.add_argument("--out", default="STREAM_BENCH.json")
    args = ap.parse_args()

    if args.devices > 1:
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count"
                     f"={args.devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import numpy as np

    all_devices = devices_with_timeout(900)
    platform = all_devices[0].platform
    serve_devices = (all_devices[:args.devices] if args.devices > 0
                     else all_devices)
    print(f"platform={platform} serve_devices={len(serve_devices)}",
          flush=True)

    from e2e_bench import PlantedModel, planted_maps

    from improved_body_parts_tpu.config import (
        InferenceModelParams, get_config)
    from improved_body_parts_tpu.infer.predict import Predictor
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs import Registry, RunTelemetry
    from improved_body_parts_tpu.serve import DynamicBatcher
    from improved_body_parts_tpu.stream import (
        FastPathConfig, SessionManager, SyntheticVideo)
    from improved_body_parts_tpu.utils.precision import resolve_params_dtype

    cfg = get_config(args.config)
    model = build_model(cfg)
    rng = np.random.default_rng(0)

    import jax.numpy as jnp

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, args.size, args.size, 3)),
                           train=False)
    variables = resolve_params_dtype(args.params_dtype, variables)
    if args.planted > 0:
        canvas = (args.planted_canvas if args.planted_canvas > 0
                  else max(int(args.size / 0.6) + 64, 640))
        model = PlantedModel(model, planted_maps(cfg.skeleton,
                                                 args.planted, rng,
                                                 canvas=canvas),
                             cfg.skeleton)
    model_params = (InferenceModelParams(boxsize=args.boxsize)
                    if args.boxsize else None)
    pred = Predictor(model, variables, cfg.skeleton,
                     model_params=model_params)

    videos = [_Video(SyntheticVideo(seed=i, num_people=args.people,
                                    size=(args.size, args.size),
                                    num_frames=args.video_frames))
              for i in range(args.streams)]

    fp_cfg = None
    if args.fastpath:
        fp_cfg = FastPathConfig(
            max_skip_run=args.fp_max_skip_run,
            min_stable=args.fp_min_stable,
            roi_width=args.fp_roi_width,
            roi_margin=args.fp_roi_margin,
            full_refresh_every=args.fp_full_refresh_every,
            people_delta=args.fp_people_delta)

    sink_path = None
    if args.telemetry_sink not in ("none", ""):
        sink_path = (os.path.splitext(args.out)[0] + "_events.jsonl"
                     if args.telemetry_sink == "auto"
                     else args.telemetry_sink)
    telemetry = RunTelemetry(
        sink_path, registry=Registry(),
        http_port=(args.telemetry_port if args.telemetry_port >= 0
                   else None),
        run_meta={"tool": "stream_bench", "config": args.config,
                  "platform": platform})
    if telemetry.server is not None:
        print(f"telemetry: {telemetry.server.url}/metrics", flush=True)

    report = {
        "platform": platform, "config": args.config,
        "streams": args.streams, "frames_per_stream": args.frames,
        "people_per_stream": args.people, "size": args.size,
        "policy": args.policy, "max_in_flight": args.max_in_flight,
        "smoothing": args.smoothing, "rounds": args.rounds,
        "max_batch": args.max_batch, "max_wait_ms": args.max_wait_ms,
        "planted_people": args.planted,
        "serve_devices": len(serve_devices),
        "telemetry_events": sink_path,
        "fastpath_mode": bool(args.fastpath),
        "note": "closed-loop streams bounded by max_in_flight; rounds "
                "interleave the N-stream arm and a serial (depth-1) "
                "1-stream baseline so host drift hits both equally "
                "(ROADMAP standing protocol: "
                "absolute imgs/s on a shared-core CPU host is noise — "
                "the per-round ratio and the sustained/recompile/drop "
                "verdicts are the signal). Planted maps are static, so "
                "every frame decodes the same crowd and track ids must "
                "hold for the whole stream.",
    }
    if args.fastpath:
        import dataclasses

        report["fastpath_config"] = dataclasses.asdict(fp_cfg)
        report["fastpath_note"] = (
            "fastpath A/B: rounds interleave a fastpath-on and a "
            "fastpath-off N-stream arm over the SAME engine, so host "
            "drift hits both equally; the verdict is the median "
            "per-round aggregate-fps ratio (sustained-streams "
            "multiplier at fixed host capacity).  Throughput arms run "
            "the planted model (honest device time); planting is "
            "content-blind, so crop decodes can disagree with "
            "full-frame decodes on person COUNT — fp-people-delta "
            "tolerates that artifact in the throughput arm while the "
            "quality block re-runs both arms over the ground-truth "
            "DetectionEngine (crops answered honestly, people_delta=0) "
            "on the static and slow_pan scene protocols and gates "
            "EQUAL synthetic-AP and IDSW.")

    def flush():
        with open(args.out, "w") as f:
            strict_dump(report, f, indent=2)

    smoothing = None if args.smoothing == "none" else args.smoothing
    with DynamicBatcher(pred, max_batch=args.max_batch,
                        max_wait_ms=args.max_wait_ms,
                        max_queue=args.max_queue,
                        decode_workers=args.decode_workers,
                        use_native=not args.no_native,
                        devices=serve_devices,
                        registry=telemetry.registry) as server:
        # the fast path's ROI tier lands in exactly ONE extra lane
        # bucket (full height, roi_width) — precompiled here with the
        # full-frame bucket so the 0-post-warmup-recompile gate covers
        # both tiers
        warm_shapes = [(args.size, args.size)]
        if fp_cfg is not None and 0 < fp_cfg.roi_width < args.size:
            warm_shapes.append((args.size, fp_cfg.roi_width))
        warm = server.warmup(warm_shapes)
        report["warmup"] = {
            "bucket_shapes": [list(s) for s in warm["bucket_shapes"]],
            "batch_sizes": list(warm["batch_sizes"]),
            "newly_compiled": warm["newly_compiled"]}
        manager = SessionManager(server, registry=telemetry.registry,
                                 smoothing=smoothing,
                                 max_in_flight=args.max_in_flight,
                                 policy=args.policy)
        # non-pow2 chunk-join occupancies are warmed by server.warmup
        # itself now (the shared serve.warmup path absorbed this
        # bench's PR 10 finding); one untimed traffic slice on top
        # (the sessions' own paths)
        run_streams(manager, videos, max(4, args.max_batch), args.policy)
        if fp_cfg is not None:
            # warm the fast-path code paths too (tracker tier, ROI
            # crop + paste-back) so neither A/B arm pays first-use cost
            run_streams(manager, videos, max(4, args.max_batch),
                        args.policy, fastpath=fp_cfg)
        telemetry.mark_warm("stream warmup precompile + warm slice")
        rounds = []
        watch = telemetry.compile_watch
        for r in range(max(1, args.rounds)):
            if args.fastpath:
                # fastpath A/B round: the SAME N streams, with and
                # without the temporal-coherence tiers, back to back —
                # per-arm compile deltas prove neither arm recompiles
                c0 = int(watch.recompiles.value)
                wall_f, snaps_f, stable_f = run_streams(
                    manager, videos, args.frames, args.policy,
                    fastpath=fp_cfg)
                on = arm_summary(wall_f, snaps_f, stable_f)
                on["recompile_delta"] = int(watch.recompiles.value) - c0
                on["fastpath"] = fastpath_block(snaps_f)
                c0 = int(watch.recompiles.value)
                wall_b, snaps_b, stable_b = run_streams(
                    manager, videos, args.frames, args.policy)
                off = arm_summary(wall_b, snaps_b, stable_b)
                off["recompile_delta"] = int(watch.recompiles.value) - c0
                rounds.append({"fastpath_on": on, "fastpath_off": off})
                report["rounds_detail"] = rounds
                flush()
                telemetry.emit(
                    "stream_fastpath_round", round=r,
                    on_aggregate_fps=on["aggregate_fps"],
                    off_aggregate_fps=off["aggregate_fps"],
                    skip_rate=on["fastpath"]["skip_rate"],
                    conservation_exact=on["fastpath"]["conservation"][
                        "exact"])
                print(f"round {r}: fastpath {on['aggregate_fps']} fps "
                      f"agg (skip {on['fastpath']['skip_rate']}, roi "
                      f"{on['fastpath']['roi_rate']}) vs baseline "
                      f"{off['aggregate_fps']} fps", flush=True)
                continue
            wall_m, snaps_m, stable_m = run_streams(
                manager, videos, args.frames, args.policy)
            multi = arm_summary(wall_m, snaps_m, stable_m)
            # baseline arm: ONE webcam driven serially (submit -> wait
            # -> next, depth 1) — the naive single-stream deployment the
            # concurrent pipelined engine is measured against
            wall_s, snaps_s, stable_s = run_streams(
                manager, videos[:1], args.frames, args.policy,
                max_in_flight=1)
            single = arm_summary(wall_s, snaps_s, stable_s)
            rounds.append({"multi": multi, "single": single})
            report["rounds_detail"] = rounds
            flush()
            telemetry.emit(
                "stream_round", round=r,
                multi_aggregate_fps=multi["aggregate_fps"],
                single_fps=single["per_stream_fps"][0],
                dropped=multi["frames_dropped"])
            print(f"round {r}: multi {multi['aggregate_fps']} fps agg "
                  f"(per-stream {multi['per_stream_fps']}) vs single "
                  f"{single['per_stream_fps'][0]} fps", flush=True)
        serve_snap = server.metrics.snapshot()
        manager.close_all(timeout_s=60)

    report["mean_batch_occupancy"] = serve_snap["mean_batch_occupancy"]
    report["occupancy_histogram"] = serve_snap["occupancy_histogram"]
    report["decode_fused"] = serve_snap["decode_fused"]
    report["decode_host_fallback"] = serve_snap["decode_host_fallback"]
    # the engine-side per-hop decomposition (queue/batch_formation/
    # device/decode/deliver) behind the streams' e2e numbers, with the
    # conservation readout (serve.metrics.HOPS)
    report["engine_hops_ms"] = serve_snap["hops_ms"]
    report["engine_hop_conservation_frac"] = \
        serve_snap["hop_conservation_frac"]
    report["recompiles_post_warmup"] = int(
        telemetry.compile_watch.recompiles.value)

    if args.fastpath:
        arms = ("fastpath_on", "fastpath_off")
        last = rounds[-1]["fastpath_on"]
        report["per_stream_fps"] = last["per_stream_fps"]
        report["per_stream_p50_ms"] = last["per_stream_p50_ms"]
        report["per_stream_p95_ms"] = last["per_stream_p95_ms"]
        ratios = sorted(
            r["fastpath_on"]["aggregate_fps"]
            / max(r["fastpath_off"]["aggregate_fps"], 1e-9)
            for r in rounds)
        report["per_round_fastpath_speedup"] = [round(x, 3)
                                               for x in ratios]
        report["median_fastpath_speedup"] = round(
            ratios[len(ratios) // 2], 3)
        report["fastpath_speedup_gate"] = args.fp_gate
        report["fastpath_speedup_sustained"] = bool(
            report["median_fastpath_speedup"] >= args.fp_gate)
        # whole-run three-tier ledger: every round's sessions are
        # fresh, so counters SUM exactly; the run is exact iff every
        # round's per-stream + summed invariants all held
        keys = ("submitted", "answered_tracker", "answered_roi",
                "escalated_full", "failed", "dropped", "depth")
        cons = {k: sum(r["fastpath_on"]["fastpath"]["conservation"][k]
                       for r in rounds) for k in keys}
        cons["exact"] = all(
            r["fastpath_on"]["fastpath"]["conservation"]["exact"]
            for r in rounds)
        esc = {}
        for r in rounds:
            for reason, n in r["fastpath_on"]["fastpath"][
                    "escalations"].items():
                esc[reason] = esc.get(reason, 0) + n
        report["fastpath_conservation"] = cons
        report["fastpath_escalations"] = esc
        report["fastpath_tier_latency_ms"] = \
            last["fastpath"]["tier_latency_ms"]
        report["fastpath_skip_rate"] = round(
            cons["answered_tracker"] / max(cons["submitted"], 1), 4)
        report["fastpath_roi_rate"] = round(
            cons["answered_roi"] / max(cons["submitted"], 1), 4)
        report["fastpath_arm_recompile_delta_total"] = sum(
            r["fastpath_on"]["recompile_delta"] for r in rounds)
        report["baseline_arm_recompile_delta_total"] = sum(
            r["fastpath_off"]["recompile_delta"] for r in rounds)
        delivered = sum(r[a]["frames_delivered"]
                        for r in rounds for a in arms)
        dropped = sum(r[a]["frames_dropped"]
                      for r in rounds for a in arms)
        failed = sum(r[a]["frames_failed"]
                     for r in rounds for a in arms)
        report["frames_delivered_total"] = delivered
        report["frames_dropped_total"] = dropped
        report["frames_failed_total"] = failed
        report["engine_shed_retries_total"] = sum(
            r[a]["engine_shed_retries"] for r in rounds for a in arms)
        # id stability is gated on the honest quality arms below; over
        # the content-blind planted model the fastpath arm's ROI crops
        # can legitimately decode extra people (reported, not gated)
        report["track_ids_stable_all_rounds"] = all(
            r["fastpath_off"]["track_ids_stable"] for r in rounds)
        report["fastpath_track_ids_stable_all_rounds"] = all(
            r["fastpath_on"]["track_ids_stable"] for r in rounds)
        min_fps = min(min(r[a]["per_stream_fps"])
                      for r in rounds for a in arms)
        report["min_stream_fps"] = round(min_fps, 3)
        report["all_streams_sustained"] = bool(
            min_fps > 0.0 and failed == 0
            and (dropped == 0 or args.policy == "drop_oldest"))
        # deterministic quality protocols: both arms over the
        # ground-truth DetectionEngine (honest crops, people_delta=0),
        # static + slow_pan scenes — the fast path must buy its
        # forwards savings at EXACTLY equal synthetic-AP and IDSW
        import dataclasses

        q_cfg = dataclasses.replace(fp_cfg, people_delta=0)
        quality = {}
        for scene in ("static", "slow_pan"):
            q_on = quality_arm(scene, args.fp_quality_frames, args.size,
                               args.people, 3, q_cfg)
            q_off = quality_arm(scene, args.fp_quality_frames,
                                args.size, args.people, 3, None)
            quality[scene] = {
                "fastpath_on": q_on,
                "fastpath_off": q_off,
                "ap_equal": bool(q_on["synthetic_ap"]
                                 == q_off["synthetic_ap"]),
                "idsw_equal": bool(q_on["identity_switches"]
                                   == q_off["identity_switches"]),
                "forwards_saved_frac": round(
                    1.0 - q_on["engine_forwards"]
                    / max(q_off["engine_forwards"], 1), 4),
            }
            print(f"quality[{scene}]: ap {q_on['synthetic_ap']} vs "
                  f"{q_off['synthetic_ap']}, idsw "
                  f"{q_on['identity_switches']} vs "
                  f"{q_off['identity_switches']}, forwards "
                  f"{q_on['engine_forwards']} vs "
                  f"{q_off['engine_forwards']}", flush=True)
        report["quality"] = quality
        report["quality_equal_all_scenes"] = all(
            q["ap_equal"] and q["idsw_equal"] for q in quality.values())
        telemetry.emit(
            "stream_fastpath_verdict",
            median_fastpath_speedup=report["median_fastpath_speedup"],
            fastpath_speedup_sustained=report[
                "fastpath_speedup_sustained"],
            quality_equal_all_scenes=report["quality_equal_all_scenes"],
            fastpath_conservation_exact=cons["exact"],
            recompiles_post_warmup=report["recompiles_post_warmup"])
        telemetry.close()
        flush()
        print(strict_dumps({
            "fastpath_speedup_sustained":
                report["fastpath_speedup_sustained"],
            "median_fastpath_speedup":
                report["median_fastpath_speedup"],
            "quality_equal_all_scenes":
                report["quality_equal_all_scenes"],
            "fastpath_conservation_exact": cons["exact"],
            "recompiles_post_warmup": report["recompiles_post_warmup"]}))
        return

    last = rounds[-1]["multi"]
    report["per_stream_fps"] = last["per_stream_fps"]
    report["per_stream_p50_ms"] = last["per_stream_p50_ms"]
    report["per_stream_p95_ms"] = last["per_stream_p95_ms"]
    ratios = sorted(
        r["multi"]["aggregate_fps"] / max(r["single"]["per_stream_fps"][0],
                                          1e-9)
        for r in rounds)
    report["per_round_scaling_ratio"] = [round(x, 3) for x in ratios]
    report["median_scaling_ratio"] = round(ratios[len(ratios) // 2], 3)
    report["engine_scales_with_streams"] = bool(
        report["median_scaling_ratio"] > 1.0)
    delivered = sum(r["multi"]["frames_delivered"] for r in rounds)
    dropped = sum(r["multi"]["frames_dropped"] for r in rounds)
    failed = sum(r["multi"]["frames_failed"] for r in rounds)
    report["frames_delivered_total"] = delivered
    report["frames_dropped_total"] = dropped
    report["frames_failed_total"] = failed
    report["engine_shed_retries_total"] = sum(
        r["multi"]["engine_shed_retries"] for r in rounds)
    report["track_ids_stable_all_rounds"] = all(
        r["multi"]["track_ids_stable"] for r in rounds)
    # the sustained verdict: every stream of every multi round delivered
    # frames at a nonzero rate, nothing failed, and (block policy)
    # nothing was dropped
    min_fps = min(min(r["multi"]["per_stream_fps"]) for r in rounds)
    report["min_stream_fps"] = round(min_fps, 3)
    report["all_streams_sustained"] = bool(
        min_fps > 0.0 and failed == 0
        and (dropped == 0 or args.policy == "drop_oldest"))
    telemetry.emit("stream_verdict",
                   median_scaling_ratio=report["median_scaling_ratio"],
                   all_streams_sustained=report["all_streams_sustained"],
                   recompiles_post_warmup=report[
                       "recompiles_post_warmup"])
    telemetry.close()
    flush()
    print(strict_dumps({
        "all_streams_sustained": report["all_streams_sustained"],
        "median_scaling_ratio": report["median_scaling_ratio"],
        "recompiles_post_warmup": report["recompiles_post_warmup"]}))


if __name__ == "__main__":
    main()
