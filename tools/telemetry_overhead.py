#!/usr/bin/env python
"""Telemetry overhead check: the full stack must cost <2% of step time.

Runs the REAL ``train_epoch`` loop (jitted step, ``device_prefetch``,
throttled readback) over synthetic batches twice per round — telemetry
OFF, then ON (JSONL sink + data-wait/compute attribution + compile
watch + registry gauges + span tracing + window memory sampling + the
health-sentinel step variant's on-device grad-norm scalar) — in
interleaved ABBA rounds (the arm order
flips each round) so host-load drift hits both arms equally, with no
systematic penalty for whichever arm runs second.

The verdict is the MEDIAN of PAIRED per-window ratios: each
print_freq-step window of an ON epoch is ratioed against the same-index
window of the temporally-adjacent OFF epoch, and the median over all
pairs is the overhead.  Estimator selection was empirical, on a
cpu-shares-throttled host whose round-to-round spread on IDENTICAL code
reached 2.5x: whole-epoch minima mis-ranked an A/A comparison by 21%,
while the paired-window median read the same A/A at ~2% and a true
OFF/ON at ~0% — pairing cancels load drift (adjacent windows see
correlated throttling) and the median discards burst-inflated pairs.
If the verdict still exceeds the budget, one adaptive retry doubles the
round count before the final answer (noise shrinks with samples; real
overhead would not).  Window minima and per-round epoch times are
reported alongside.  Also verifies the ON arm's event stream actually
parses and its wait+compute split covers the epoch wall time.

Registered as the ``"telemetry"`` key in bench.py
(``IBP_BENCH_TELEMETRY=0`` skips).

    python tools/telemetry_overhead.py            # 10 steps x 15 rounds
"""
import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

OVERHEAD_BUDGET_PCT = 2.0


def paired_median_overhead(off_fps, on_fps):
    """Overhead %% = median of paired per-round off/on throughput
    ratios — the TELEMETRY_OVERHEAD estimator on the serve path.

    Estimator notes (empirical, same discipline as the train-path
    selection): on a cpu-shares host under EXTERNAL load a single
    round's paired ratio swings ±70%% in both directions and no
    round-level estimator is sound — the adaptive retry (double the
    pairs, re-estimate over all of them) is the defense, and the
    committed artifact runs on an otherwise-idle host where 24 pairs
    sit within ~±8%% and the median stabilizes to ~1-2%%.  Selecting a
    "quiet" SUBSET of pairs by their own throughput was tried and
    rejected: it preferentially keeps rounds where the off arm drew
    high, biasing the median upward by >2×."""
    ratios = sorted(o / n for o, n in zip(off_fps, on_fps))
    return (ratios[len(ratios) // 2] - 1.0) * 100.0


def serve_overhead_ab(predictor, sizes, images, n_clients, requests,
                      rounds, batcher_kw=None, tmpdir=None,
                      budget_pct=OVERHEAD_BUDGET_PCT, on_warm=None):
    """Serve-path reqtrace A/B: closed-loop slices against ONE warm
    batcher, alternating the full request-tracing stack OFF and ON
    (``obs.reqtrace.ReqTrace`` sample=1 + JSONL sink + span tracer —
    what a traced serving process actually pays per request), ABBA
    round order, verdict = median of paired per-round throughput
    ratios.  The same TELEMETRY_OVERHEAD estimator discipline as the
    train-path A/B: pairing cancels host-load drift, the median
    discards burst-inflated rounds, and one adaptive retry doubles the
    evidence before concluding the budget is blown.  The per-hop
    boundary stamps (``serve.metrics.HOPS``) run in BOTH arms — they
    are part of the serve path now, five perf_counter reads per
    request; this A/B prices the *recorder* (tree assembly + JSONL
    emission), which is the part sampling can thin.

    Importable: ``tools/latency_audit.py`` embeds this verdict in
    LATENCY_AUDIT.json.
    """
    from improved_body_parts_tpu.obs import (
        EventSink, ReqTrace, TraceRecorder, set_reqtrace, set_sink,
        set_tracer)
    from improved_body_parts_tpu.serve import (
        DynamicBatcher, submit_with_retry)

    tmpdir = tmpdir or tempfile.mkdtemp(prefix="reqtrace_oh_")
    events_path = os.path.join(tmpdir, "serve_events.jsonl")

    def run_slice(server):
        import threading

        errors = []

        def client(cid):
            try:
                for i in range(requests):
                    img = images[(cid + i * n_clients) % len(images)]
                    fut, _ = submit_with_retry(server.submit, img,
                                               base_s=0.002, max_s=0.05)
                    fut.result()
            except Exception as e:  # noqa: BLE001 — surfaced after join
                errors.append(e)

        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True)
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return n_clients * requests / wall

    def measure(n_rounds):
        off_fps, on_fps = [], []
        for i in range(n_rounds):
            order = [("off", off_fps), ("on", on_fps)]
            if i % 2:
                order.reverse()
            for arm, acc in order:
                if arm == "on":
                    sink = EventSink(events_path,
                                     run_meta={"tool": "serve_ab"})
                    installs = (set_sink(sink),
                                set_reqtrace(ReqTrace(sample=1,
                                                      t0=sink.t0)),
                                set_tracer(TraceRecorder(t0=sink.t0)))
                else:
                    # the OFF arm must install the NULL stack
                    # explicitly — when the CALLER runs under a live
                    # RunTelemetry (latency_audit does), inheriting its
                    # recorder/sink/tracer would silently turn this
                    # A/B into an A/A (and leak the off rounds'
                    # records into the caller's stream)
                    sink = None
                    installs = (set_sink(None), set_reqtrace(None),
                                set_tracer(None))
                try:
                    acc.append(run_slice(server))
                finally:
                    prev_sink, prev_rt, prev_tr = installs
                    set_tracer(prev_tr)
                    set_reqtrace(prev_rt)
                    set_sink(prev_sink)
                    if sink is not None:
                        sink.close()
        return paired_median_overhead(off_fps, on_fps), off_fps, on_fps

    kw = dict(batcher_kw or {})
    # ONE warm server for both arms: identical compiled programs and
    # thread pools, so the only difference a round sees is the
    # installed recorder stack
    server = DynamicBatcher(predictor, **kw)
    with server:
        server.warmup(sizes)
        if on_warm is not None:
            # the caller's warm fence (latency_audit anchors its
            # per-arm recompile delta here)
            on_warm()
        overhead_pct, off_fps, on_fps = measure(max(1, rounds))
        retried = False
        if overhead_pct >= budget_pct:
            # noise shrinks with samples, real overhead would not:
            # double the evidence once and re-estimate over ALL pairs
            retried = True
            _, off2, on2 = measure(max(1, rounds) * 2)
            off_fps += off2
            on_fps += on2
            overhead_pct = paired_median_overhead(off_fps, on_fps)
    n_events = sum(1 for line in open(events_path))
    return {
        "estimator": "median of paired per-round off/on throughput "
                     "ratios, ABBA order, adaptive retry pooling all "
                     "pairs (see paired_median_overhead)",
        "clients": n_clients,
        "requests_per_round": n_clients * requests,
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": budget_pct,
        "within_budget": bool(overhead_pct < budget_pct),
        "retried": retried,
        "off_imgs_per_sec": [round(v, 3) for v in off_fps],
        "on_imgs_per_sec": [round(v, 3) for v in on_fps],
        "on_events_emitted": n_events,
        "events": events_path,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--steps", type=int, default=10,
                    help="train steps per arm per round — SHORT epochs "
                         "keep paired windows temporally adjacent, so "
                         "cpu-shares throttle bursts hit both arms of a "
                         "pair (validated: 10x15 reads a loaded host "
                         "within ±2%% where 30x5 spread 4-8%%)")
    ap.add_argument("--rounds", type=int, default=15,
                    help="interleaved off/on rounds; more rounds = more "
                         "window pairs = tighter noise immunity (a "
                         "shared-core host's spread on identical code "
                         "can be several times the true overhead)")
    ap.add_argument("--print-freq", type=int, default=5)
    ap.add_argument("--serve-path", action="store_true",
                    help="also run the serve-path reqtrace A/B (closed-"
                         "loop clients against one warm batcher, "
                         "request tracing off vs on) and report it as "
                         "the serve_path block")
    ap.add_argument("--serve-rounds", type=int, default=6,
                    help="serve-path A/B rounds (ABBA paired)")
    ap.add_argument("--serve-clients", type=int, default=2)
    ap.add_argument("--serve-requests", type=int, default=6,
                    help="closed-loop requests per client per round")
    ap.add_argument("--serve-size", type=int, default=128,
                    help="square frame size for the serve-path arm "
                         "(small = fast rounds AND a conservatively "
                         "LARGE relative overhead)")
    ap.add_argument("--out", default="TELEMETRY_OVERHEAD.json")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when the overhead budget is blown")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import jax
    import numpy as np

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs import (
        Registry, RunTelemetry, read_events, set_tracer)
    from improved_body_parts_tpu.parallel import make_mesh, replicated
    from improved_body_parts_tpu.train import (
        create_train_state, make_optimizer, make_train_step,
        step_decay_schedule)
    from improved_body_parts_tpu.train.loop import train_epoch

    cfg = get_config(args.config)
    model = build_model(cfg)
    mesh = make_mesh()
    n_dev = int(mesh.devices.size)
    batch = max(cfg.train.batch_size_per_device, 1) * n_dev
    size = cfg.skeleton.height
    grid = size // cfg.skeleton.stride
    rng = np.random.default_rng(0)

    imgs = rng.uniform(0, 1, (batch, size, size, 3)).astype(np.float32)
    labels = rng.uniform(
        0, 1, (batch, grid, grid, cfg.skeleton.num_layers)
    ).astype(np.float32)
    mask = np.ones((batch, grid, grid, 1), np.float32)

    def batches(ticks=None):
        for _ in range(args.steps):
            if ticks is not None:
                ticks.append(time.perf_counter())
            yield (imgs, mask, labels)

    opt = make_optimizer(cfg, step_decay_schedule(cfg.train,
                                                  steps_per_epoch=100))
    state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                               imgs[:1])
    state = jax.device_put(state, replicated(mesh))
    # the ON arm runs the FULL instrumented stack — the health-sentinel
    # step variant (one extra on-device scalar: the global grad norm),
    # span tracing, window memory sampling — so the verdict prices what
    # a real telemetry-on run pays, not just the sink
    step_off = make_train_step(model, cfg, opt)
    step_on = make_train_step(model, cfg, opt, health=True)
    quiet = lambda s: None  # noqa: E731 — stdout must stay one JSON line

    # untimed compile pass for BOTH programs (each arm then reuses its
    # compiled step; alternating donation across the two is fine — every
    # call donates the current state and returns the next)
    state, _ = train_epoch(state, step_off, batches(), cfg, 0, mesh=mesh,
                           print_freq=args.print_freq, log_fn=quiet)
    state, _ = train_epoch(state, step_on, batches(), cfg, 0, mesh=mesh,
                           print_freq=args.print_freq, log_fn=quiet)

    events_path = os.path.join(tempfile.mkdtemp(prefix="telemetry_oh_"),
                               "events.jsonl")
    tele = RunTelemetry(events_path, registry=Registry(),
                        run_meta={"tool": "telemetry_overhead",
                                  "config": args.config})

    def run_arm(telemetry, epochs, windows):
        """One epoch; appends its per-print_freq-window step times (the
        batch-iterator tick deltas — identical apparatus in both arms)
        as one list, and the whole-epoch per-step time."""
        nonlocal state, on_wall
        ticks = []
        step = step_on if telemetry is tele else step_off
        # the bundle installs its tracer process-wide (that is the
        # feature: unplumbed sites like the prefetch producer find it);
        # the OFF arm must not record through it or the A/B loses part
        # of the very cost it prices
        prev_tracer = set_tracer(None) if telemetry is None else None
        t0 = time.perf_counter()
        try:
            state, _ = train_epoch(state, step, batches(ticks), cfg, 1,
                                   mesh=mesh, print_freq=args.print_freq,
                                   log_fn=quiet, telemetry=telemetry)
        finally:
            if telemetry is None:
                set_tracer(prev_tracer)
        t1 = time.perf_counter()
        ticks.append(t1)
        w = args.print_freq
        windows.append([(ticks[i + w] - ticks[i]) / w
                        for i in range(0, len(ticks) - w, w)])
        epochs.append((t1 - t0) / args.steps)
        if telemetry is tele:
            on_wall += t1 - t0

    off, on = [], []          # per-epoch step time, per round
    off_w, on_w = [], []      # per-round lists of window step times
    on_wall = 0.0

    def measure(rounds, round0):
        for i in range(round0, round0 + rounds):
            # ABBA order: alternate which arm goes first each round, so
            # a monotonic host-load ramp cannot systematically penalize
            # one arm
            order = [(off, off_w, None), (on, on_w, tele)]
            if i % 2:
                order.reverse()
            for epochs, windows, t in order:
                run_arm(t, epochs, windows)
        ratios = [b / a
                  for ar, br in zip(off_w, on_w)
                  for a, b in zip(ar, br)]
        return (statistics.median(ratios) - 1.0) * 100.0, len(ratios)

    rounds = max(1, args.rounds)
    overhead_pct, pairs = measure(rounds, 0)
    retried = False
    if overhead_pct >= OVERHEAD_BUDGET_PCT:
        # over budget: noise shrinks with samples, real overhead would
        # not — double the evidence once before concluding
        retried = True
        overhead_pct, pairs = measure(rounds, rounds)
    trace_spans = tele.trace.recorded
    health_state = tele.health.state()
    tele.close()

    flat_off = [v for ws in off_w for v in ws]
    flat_on = [v for ws in on_w for v in ws]
    step_off = min(flat_off)
    step_on = min(flat_on)

    # the ON arm's stream must parse, and its attributed split must
    # cover the loop's wall time (the report's verdict depends on it)
    events = read_events(events_path)
    records = [e for e in events if e.get("event") == "train_step"]
    wait = sum(e["data_wait_s"] for e in records)
    hold = sum(e["compute_s"] for e in records)
    split_cover = (wait + hold) / on_wall if on_wall else 0.0

    serve_path = None
    if args.serve_path:
        from e2e_bench import PlantedModel, planted_maps, synth_images

        from improved_body_parts_tpu.config import (
            InferenceModelParams, get_config)
        from improved_body_parts_tpu.infer.predict import Predictor

        s_cfg = get_config("tiny")
        s_model = build_model(s_cfg)
        sz = args.serve_size
        import jax.numpy as jnp

        s_vars = s_model.init(jax.random.PRNGKey(0),
                              jnp.zeros((1, sz, sz, 3)), train=False)
        s_rng = np.random.default_rng(0)
        planted = PlantedModel(
            s_model, planted_maps(s_cfg.skeleton, 2, s_rng,
                                  canvas=max(sz * 2, 256)),
            s_cfg.skeleton)
        s_pred = Predictor(planted, s_vars, s_cfg.skeleton,
                           model_params=InferenceModelParams(
                               boxsize=sz, max_downsample=64),
                           bucket=64)
        serve_path = serve_overhead_ab(
            s_pred, [(sz, sz)], synth_images(4, sz, s_rng),
            args.serve_clients, args.serve_requests, args.serve_rounds,
            batcher_kw=dict(max_batch=4, max_wait_ms=10.0))
        print(strict_dumps({"serve_path_overhead_pct":
                            serve_path["overhead_pct"]}))

    report = {
        "config": args.config,
        "steps": args.steps,
        "rounds": args.rounds,
        "estimator": "median of paired per-window on/off ratios "
                     "(ABBA rounds; see module docstring)",
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": OVERHEAD_BUDGET_PCT,
        "within_budget": bool(overhead_pct < OVERHEAD_BUDGET_PCT),
        "window_pairs": pairs,
        "retried": retried,
        "step_ms_off": round(step_off * 1e3, 3),   # best window per arm
        "step_ms_on": round(step_on * 1e3, 3),
        "step_ms_off_median": round(
            statistics.median(flat_off) * 1e3, 3),
        "step_ms_on_median": round(statistics.median(flat_on) * 1e3, 3),
        "per_round_off_ms": [round(v * 1e3, 3) for v in off],
        "per_round_on_ms": [round(v * 1e3, 3) for v in on],
        # the OFF arm's own round-to-round spread: the measurement noise
        # floor indicator — identical code has been measured spreading
        # 2-2.5x round-to-round on a shared-core host
        "off_round_spread_pct": round(
            (max(off) - min(off)) / min(off) * 100.0, 2),
        "telemetry_events": events_path,
        "events_parsed": len(events),
        "step_records": len(records),
        # the ON arm runs the whole second-floor stack; prove it did
        "trace_spans": trace_spans,
        "health_checks": health_state["checks"],
        "health_status": health_state["status"],
        "memory_samples": sum(
            1 for e in events if e.get("event") == "memory"),
        "split_covers_wall_frac": round(split_cover, 4),
        "recompiles_post_warmup": sum(
            1 for e in events if e.get("event") == "recompile"),
        **({"serve_path": serve_path} if serve_path is not None else {}),
    }
    with open(args.out, "w") as f:
        strict_dump(report, f, indent=2)
    print(strict_dumps(report))
    if args.strict and not report["within_budget"]:
        sys.exit(1)
    if args.strict and serve_path is not None \
            and not serve_path["within_budget"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
