#!/usr/bin/env python
"""Telemetry overhead check: the full stack must cost <2% of step time.

Runs the REAL ``train_epoch`` loop (jitted step, ``device_prefetch``,
throttled readback) over synthetic batches twice per round — telemetry
OFF, then ON (JSONL sink + data-wait/compute attribution + compile
watch + registry gauges + span tracing + window memory sampling + the
health-sentinel step variant's on-device grad-norm scalar) — in
interleaved ABBA rounds (the arm order
flips each round) so host-load drift hits both arms equally, with no
systematic penalty for whichever arm runs second.

The verdict is the MEDIAN of PAIRED per-window ratios: each
print_freq-step window of an ON epoch is ratioed against the same-index
window of the temporally-adjacent OFF epoch, and the median over all
pairs is the overhead.  Estimator selection was empirical, on a
cpu-shares-throttled host whose round-to-round spread on IDENTICAL code
reached 2.5x: whole-epoch minima mis-ranked an A/A comparison by 21%,
while the paired-window median read the same A/A at ~2% and a true
OFF/ON at ~0% — pairing cancels load drift (adjacent windows see
correlated throttling) and the median discards burst-inflated pairs.
If the verdict still exceeds the budget, one adaptive retry doubles the
round count before the final answer (noise shrinks with samples; real
overhead would not).  Window minima and per-round epoch times are
reported alongside.  Also verifies the ON arm's event stream actually
parses and its wait+compute split covers the epoch wall time.

Registered as the ``"telemetry"`` key in bench.py
(``IBP_BENCH_TELEMETRY=0`` skips).

    python tools/telemetry_overhead.py            # 10 steps x 15 rounds
"""
import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

OVERHEAD_BUDGET_PCT = 2.0


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--steps", type=int, default=10,
                    help="train steps per arm per round — SHORT epochs "
                         "keep paired windows temporally adjacent, so "
                         "cpu-shares throttle bursts hit both arms of a "
                         "pair (validated: 10x15 reads a loaded host "
                         "within ±2%% where 30x5 spread 4-8%%)")
    ap.add_argument("--rounds", type=int, default=15,
                    help="interleaved off/on rounds; more rounds = more "
                         "window pairs = tighter noise immunity (a "
                         "shared-core host's spread on identical code "
                         "can be several times the true overhead)")
    ap.add_argument("--print-freq", type=int, default=5)
    ap.add_argument("--out", default="TELEMETRY_OVERHEAD.json")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when the overhead budget is blown")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()

    import jax
    import numpy as np

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs import (
        Registry, RunTelemetry, read_events, set_tracer)
    from improved_body_parts_tpu.parallel import make_mesh, replicated
    from improved_body_parts_tpu.train import (
        create_train_state, make_optimizer, make_train_step,
        step_decay_schedule)
    from improved_body_parts_tpu.train.loop import train_epoch

    cfg = get_config(args.config)
    model = build_model(cfg)
    mesh = make_mesh()
    n_dev = int(mesh.devices.size)
    batch = max(cfg.train.batch_size_per_device, 1) * n_dev
    size = cfg.skeleton.height
    grid = size // cfg.skeleton.stride
    rng = np.random.default_rng(0)

    imgs = rng.uniform(0, 1, (batch, size, size, 3)).astype(np.float32)
    labels = rng.uniform(
        0, 1, (batch, grid, grid, cfg.skeleton.num_layers)
    ).astype(np.float32)
    mask = np.ones((batch, grid, grid, 1), np.float32)

    def batches(ticks=None):
        for _ in range(args.steps):
            if ticks is not None:
                ticks.append(time.perf_counter())
            yield (imgs, mask, labels)

    opt = make_optimizer(cfg, step_decay_schedule(cfg.train,
                                                  steps_per_epoch=100))
    state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                               imgs[:1])
    state = jax.device_put(state, replicated(mesh))
    # the ON arm runs the FULL instrumented stack — the health-sentinel
    # step variant (one extra on-device scalar: the global grad norm),
    # span tracing, window memory sampling — so the verdict prices what
    # a real telemetry-on run pays, not just the sink
    step_off = make_train_step(model, cfg, opt)
    step_on = make_train_step(model, cfg, opt, health=True)
    quiet = lambda s: None  # noqa: E731 — stdout must stay one JSON line

    # untimed compile pass for BOTH programs (each arm then reuses its
    # compiled step; alternating donation across the two is fine — every
    # call donates the current state and returns the next)
    state, _ = train_epoch(state, step_off, batches(), cfg, 0, mesh=mesh,
                           print_freq=args.print_freq, log_fn=quiet)
    state, _ = train_epoch(state, step_on, batches(), cfg, 0, mesh=mesh,
                           print_freq=args.print_freq, log_fn=quiet)

    events_path = os.path.join(tempfile.mkdtemp(prefix="telemetry_oh_"),
                               "events.jsonl")
    tele = RunTelemetry(events_path, registry=Registry(),
                        run_meta={"tool": "telemetry_overhead",
                                  "config": args.config})

    def run_arm(telemetry, epochs, windows):
        """One epoch; appends its per-print_freq-window step times (the
        batch-iterator tick deltas — identical apparatus in both arms)
        as one list, and the whole-epoch per-step time."""
        nonlocal state, on_wall
        ticks = []
        step = step_on if telemetry is tele else step_off
        # the bundle installs its tracer process-wide (that is the
        # feature: unplumbed sites like the prefetch producer find it);
        # the OFF arm must not record through it or the A/B loses part
        # of the very cost it prices
        prev_tracer = set_tracer(None) if telemetry is None else None
        t0 = time.perf_counter()
        try:
            state, _ = train_epoch(state, step, batches(ticks), cfg, 1,
                                   mesh=mesh, print_freq=args.print_freq,
                                   log_fn=quiet, telemetry=telemetry)
        finally:
            if telemetry is None:
                set_tracer(prev_tracer)
        t1 = time.perf_counter()
        ticks.append(t1)
        w = args.print_freq
        windows.append([(ticks[i + w] - ticks[i]) / w
                        for i in range(0, len(ticks) - w, w)])
        epochs.append((t1 - t0) / args.steps)
        if telemetry is tele:
            on_wall += t1 - t0

    off, on = [], []          # per-epoch step time, per round
    off_w, on_w = [], []      # per-round lists of window step times
    on_wall = 0.0

    def measure(rounds, round0):
        for i in range(round0, round0 + rounds):
            # ABBA order: alternate which arm goes first each round, so
            # a monotonic host-load ramp cannot systematically penalize
            # one arm
            order = [(off, off_w, None), (on, on_w, tele)]
            if i % 2:
                order.reverse()
            for epochs, windows, t in order:
                run_arm(t, epochs, windows)
        ratios = [b / a
                  for ar, br in zip(off_w, on_w)
                  for a, b in zip(ar, br)]
        return (statistics.median(ratios) - 1.0) * 100.0, len(ratios)

    rounds = max(1, args.rounds)
    overhead_pct, pairs = measure(rounds, 0)
    retried = False
    if overhead_pct >= OVERHEAD_BUDGET_PCT:
        # over budget: noise shrinks with samples, real overhead would
        # not — double the evidence once before concluding
        retried = True
        overhead_pct, pairs = measure(rounds, rounds)
    trace_spans = tele.trace.recorded
    health_state = tele.health.state()
    tele.close()

    flat_off = [v for ws in off_w for v in ws]
    flat_on = [v for ws in on_w for v in ws]
    step_off = min(flat_off)
    step_on = min(flat_on)

    # the ON arm's stream must parse, and its attributed split must
    # cover the loop's wall time (the report's verdict depends on it)
    events = read_events(events_path)
    records = [e for e in events if e.get("event") == "train_step"]
    wait = sum(e["data_wait_s"] for e in records)
    hold = sum(e["compute_s"] for e in records)
    split_cover = (wait + hold) / on_wall if on_wall else 0.0

    report = {
        "config": args.config,
        "steps": args.steps,
        "rounds": args.rounds,
        "estimator": "median of paired per-window on/off ratios "
                     "(ABBA rounds; see module docstring)",
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": OVERHEAD_BUDGET_PCT,
        "within_budget": bool(overhead_pct < OVERHEAD_BUDGET_PCT),
        "window_pairs": pairs,
        "retried": retried,
        "step_ms_off": round(step_off * 1e3, 3),   # best window per arm
        "step_ms_on": round(step_on * 1e3, 3),
        "step_ms_off_median": round(
            statistics.median(flat_off) * 1e3, 3),
        "step_ms_on_median": round(statistics.median(flat_on) * 1e3, 3),
        "per_round_off_ms": [round(v * 1e3, 3) for v in off],
        "per_round_on_ms": [round(v * 1e3, 3) for v in on],
        # the OFF arm's own round-to-round spread: the measurement noise
        # floor indicator — identical code has been measured spreading
        # 2-2.5x round-to-round on a shared-core host
        "off_round_spread_pct": round(
            (max(off) - min(off)) / min(off) * 100.0, 2),
        "telemetry_events": events_path,
        "events_parsed": len(events),
        "step_records": len(records),
        # the ON arm runs the whole second-floor stack; prove it did
        "trace_spans": trace_spans,
        "health_checks": health_state["checks"],
        "health_status": health_state["status"],
        "memory_samples": sum(
            1 for e in events if e.get("event") == "memory"),
        "split_covers_wall_frac": round(split_cover, 4),
        "recompiles_post_warmup": sum(
            1 for e in events if e.get("event") == "recompile"),
    }
    with open(args.out, "w") as f:
        strict_dump(report, f, indent=2)
    print(strict_dumps(report))
    if args.strict and not report["within_budget"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
