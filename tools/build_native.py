#!/usr/bin/env python
"""Build the native C++ decoder (native/libposedecoder.so) with g++.

Equivalent to ``make -C native``; kept as a Python entry point so the build
works without make.
"""
import os
import subprocess
import sys

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def build(verbose: bool = True) -> str:
    """Delegate to ``make -C native`` so the compiler flags live in exactly
    one place (native/Makefile)."""
    out = os.path.join(NATIVE_DIR, "libposedecoder.so")
    cmd = ["make", "-C", NATIVE_DIR]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    path = build()
    print("built", path)
    sys.exit(0)
