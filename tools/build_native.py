#!/usr/bin/env python
"""Build the native C++ decoder (native/libposedecoder.so).

Thin Python entry point over ``make -C native`` — the Makefile is the single
source of truth for compiler flags.
"""
import os
import subprocess
import sys

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def build(verbose: bool = True) -> str:
    """Delegate to ``make -C native`` so the compiler flags live in exactly
    one place (native/Makefile)."""
    out = os.path.join(NATIVE_DIR, "libposedecoder.so")
    cmd = ["make", "-C", NATIVE_DIR]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    import argparse

    argparse.ArgumentParser(
        description="build the native C++ decoder via make").parse_args()
    path = build()
    print("built", path)
    sys.exit(0)
