#!/usr/bin/env python
"""Build the native C++ decoder (native/libposedecoder.so) with g++.

Equivalent to ``make -C native``; kept as a Python entry point so the build
works without make.
"""
import os
import subprocess
import sys

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def build(verbose: bool = True) -> str:
    src = os.path.join(NATIVE_DIR, "decoder.cpp")
    out = os.path.join(NATIVE_DIR, "libposedecoder.so")
    cmd = ["g++", "-O3", "-march=native", "-fPIC", "-std=c++17", "-Wall",
           "-Wextra", "-shared", "-o", out, src]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    path = build()
    print("built", path)
    sys.exit(0)
