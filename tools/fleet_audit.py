#!/usr/bin/env python
"""Fleet observability audit → committed ``FLEET_OBS.json``.

Proves the §7g fleet observability plane (worker-process telemetry over
the shm wire, merged ``/metrics``+``/fleet``, cross-process trace
stitching, crash flight recorder) against five gates on a LIVE
2-worker ``ProcessRouter``:

1. **Overhead** — interleaved obs-ON/obs-OFF A/B, paired per-round
   overhead, median < ``OVERHEAD_GATE_PCT``%.  The OFF arm installs the
   null sink, null tracer and ``telemetry=False`` workers EXPLICITLY
   (the documented A/A hazard: an arm that merely *forgot* to configure
   telemetry measures nothing).
2. **Conservation** — at ON-arm quiescence, router-view submitted vs
   Σ worker-view served + in-flight ≥ ``MIN_COVERAGE`` (1.0 on a clean
   run; the margin tolerates crash-lost counts when chaos is in play).
3. **Compiles** — per-arm compile-delta accounting: parent CompileWatch
   delta + every worker's own in-process compile counters (telemetry
   block on the ON arm, heartbeat float on the OFF arm) must show 0
   post-warmup recompiles.
4. **Scrape** — one live ``MetricsServer`` over the merged registry:
   ``/metrics`` must expose per-worker hop / occupancy / compile /
   memory families under ``worker=`` labels, ``/fleet`` the per-worker
   document + conservation block, ``/healthz`` the fleet extra,
   ``/slo`` the tracker state.
5. **Chaos** — one SIGKILL round mid-batch: the exhumed flight
   recorder's ``worker_postmortem`` must pass the structural verifier
   (``obs.fleet.verify_postmortem``) — it names the killed batch's
   slot/seq and last completed hop, not merely "a worker died".

Plus the trace-stitch proof: the ON arm's parent export + per-worker
``.pN`` shards stitch (``tools/trace_report.py`` machinery) into one
timeline whose ``cat="proc"`` flow arcs thread router submit → worker
serve → router deliver.

    python tools/fleet_audit.py --rounds 6 --out FLEET_OBS.json
    python tools/fleet_audit.py --quick        # CI-budget variant
"""
import argparse
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: paired-median throughput overhead the ON arm may cost, percent
OVERHEAD_GATE_PCT = 2.0
#: minimum (served + in-flight) / submitted coverage at quiescence
MIN_COVERAGE = 0.95

SPEC = "improved_body_parts_tpu.serve.worker:constant_predictor"
#: per-request simulated device time — large enough that the plane's
#: per-request cost (~tens of µs) lands well under the gate, small
#: enough that a round stays sub-second
DELAY_S = 0.003

#: /metrics families that must appear with a worker= label on the ON
#: arm: hop latency, occupancy, compiles, device memory (the ISSUE's
#: acceptance list)
REQUIRED_FAMILIES = (
    "fleet_worker_hop_latency_seconds",
    "fleet_worker_batch_occupancy_mean",
    "fleet_worker_xla_compiles_total",
    "fleet_worker_device_bytes_in_use",
    "fleet_worker_served_total",
    "fleet_worker_up",
)


def _mk_router(ProcessRouter, *, telemetry, trace_path=None, slo=None,
               delay_s=DELAY_S, workers=2, slots=8):
    return ProcessRouter(
        SPEC, num_workers=workers,
        spec_kwargs={"num_parts": 18, "n_people": 2, "delay_s": delay_s},
        slots=slots, max_image_hw=(64, 64), num_parts=18, max_people=8,
        restart_after_s=0.3, probe_interval_s=0.05,
        telemetry=telemetry, trace_path=trace_path, slo=slo)


def run_slice(router, images, n_clients, requests):
    """Closed-loop slice: n_clients threads, each ``requests``
    submit→result round-trips; returns imgs/sec."""
    from improved_body_parts_tpu.serve import submit_with_retry

    errs = []

    def work(cid):
        for i in range(requests):
            img = images[(cid + i) % len(images)]
            try:
                fut, _ = submit_with_retry(router.submit, img,
                                           base_s=0.002, max_s=0.05)
                fut.result(timeout=60)
            except Exception as e:  # noqa: BLE001 — surfaced in report
                errs.append(repr(e))
                return
    t0 = time.perf_counter()
    threads = [threading.Thread(target=work, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errs:
        raise SystemExit(f"audit slice failed: {errs[0]}")
    return round(n_clients * requests / wall, 3)


def audit(args):
    import numpy as np

    from improved_body_parts_tpu.obs.events import (
        EventSink, NullSink, set_sink)
    from improved_body_parts_tpu.obs.fleet import verify_postmortem
    from improved_body_parts_tpu.obs.health import HealthSentinel
    from improved_body_parts_tpu.obs.http import MetricsServer
    from improved_body_parts_tpu.obs.recompile import CompileWatch
    from improved_body_parts_tpu.obs.registry import Registry
    from improved_body_parts_tpu.obs.slo import (
        SLOTracker, default_objectives)
    from improved_body_parts_tpu.obs.trace import (
        NullTraceRecorder, TraceRecorder, set_tracer)
    from improved_body_parts_tpu.serve.router import ProcessRouter
    from trace_report import discover_shards, stitch_shards, summarize

    workdir = tempfile.mkdtemp(prefix="fleet_audit_")
    trace_path = os.path.join(workdir, "trace.json")
    rng = np.random.default_rng(0)
    images = [rng.integers(0, 255, (48, 48, 3), dtype=np.uint8)
              for _ in range(8)]

    # ---------------------------------------------------------- ON arm
    # real sink + tracer + telemetry=True workers; installed while the
    # ON router spawns so the run_id rides into the worker shards
    sink = EventSink(os.path.join(workdir, "events.jsonl"),
                     run_meta={"run_id": "fleet-audit"})
    tracer = TraceRecorder(capacity=65536, t0=sink.t0)
    null_tracer = NullTraceRecorder()
    null_sink = NullSink()
    set_sink(sink)
    set_tracer(tracer)
    registry = Registry()
    watch = CompileWatch(registry=registry, sink=null_sink).install()
    slo = SLOTracker(default_objectives(),
                     default_class="interactive")
    on_router = _mk_router(ProcessRouter, telemetry=True,
                           trace_path=trace_path, slo=slo)
    on_router.register_into(registry)
    on_router.start()
    on_router.warmup([(64, 64)])

    # --------------------------------------------------------- OFF arm
    # the A/A hazard rule: disable EXPLICITLY — null sink + null tracer
    # + telemetry=False (workers install NullSink/NullTraceRecorder and
    # never publish; only the 4-float heartbeat moves).  The SLO
    # tracker feeds on BOTH arms: it is the PR 15 layer, not the fleet
    # plane under test, so its per-request cost must cancel in the pair
    set_sink(null_sink)
    set_tracer(null_tracer)
    off_router = _mk_router(ProcessRouter, telemetry=False, slo=slo)
    off_router.start()
    off_router.warmup([(64, 64)])
    watch.mark_warm("fleet audit warmup")
    c_warm = int(watch.compiles.value)

    # one unmeasured slice per arm: first-touch costs (track
    # registration, ring growth, page faults on the telem block) are
    # startup, not per-request overhead
    set_sink(sink)
    set_tracer(tracer)
    run_slice(on_router, images, args.clients, args.requests)
    set_sink(null_sink)
    set_tracer(null_tracer)
    run_slice(off_router, images, args.clients, args.requests)

    report = {
        "generated_by": "tools/fleet_audit.py",
        "protocol": {
            "workers": 2, "clients": args.clients,
            "requests_per_client": args.requests,
            "rounds": args.rounds, "predictor_delay_s": DELAY_S,
            "interleaved": True,
            "off_arm": "explicit NullSink + NullTraceRecorder + "
                       "telemetry=False (never 'unconfigured')",
        },
    }

    # ------------------------------------------- 1: interleaved A/B
    on_ips, off_ips = [], []
    arm_compile_delta = {"on": 0, "off": 0}
    for rnd in range(args.rounds):
        set_sink(sink)
        set_tracer(tracer)
        c0 = int(watch.compiles.value)
        on_ips.append(run_slice(on_router, images, args.clients,
                                args.requests))
        arm_compile_delta["on"] += int(watch.compiles.value) - c0
        set_sink(null_sink)
        set_tracer(null_tracer)
        c0 = int(watch.compiles.value)
        off_ips.append(run_slice(off_router, images, args.clients,
                                 args.requests))
        arm_compile_delta["off"] += int(watch.compiles.value) - c0
        print(f"round {rnd}: on {on_ips[-1]} vs off {off_ips[-1]} "
              "imgs/s", flush=True)
    per_round = [round((off - on) / off * 100.0, 3)
                 for on, off in zip(on_ips, off_ips)]
    median_overhead = round(statistics.median(per_round), 3)
    report["overhead"] = {
        "on_imgs_per_sec": on_ips, "off_imgs_per_sec": off_ips,
        "per_round_overhead_pct": per_round,
        "paired_median_overhead_pct": median_overhead,
        "gate_pct": OVERHEAD_GATE_PCT,
        "ok": bool(median_overhead < OVERHEAD_GATE_PCT),
    }

    # restore the ON plane for the remaining gates
    set_sink(sink)
    set_tracer(tracer)

    # ------------------------------------------- 2: conservation
    cons = on_router.fleet.conservation()
    report["conservation"] = {
        **cons, "gate": MIN_COVERAGE,
        "ok": bool(cons["frac"] is not None
                   and cons["frac"] >= MIN_COVERAGE),
    }

    # ------------------------------------------- 3: compile deltas
    telem_rows = [w["telemetry"]
                  for w in on_router.fleet_state()["workers"]]
    worker_recompiles = {
        "on": sum(int(t.get("recompiles_post_warmup", 0))
                  for t in telem_rows),
        "off": sum(int(w["recompiles_post_warmup"])
                   for w in off_router.worker_stats()),
    }
    report["compiles"] = {
        "parent_warmup_compiles": c_warm,
        "parent_per_arm_delta": arm_compile_delta,
        "worker_recompiles_post_warmup": worker_recompiles,
        "ok": bool(arm_compile_delta["on"] == 0
                   and arm_compile_delta["off"] == 0
                   and worker_recompiles["on"] == 0
                   and worker_recompiles["off"] == 0),
    }

    # ------------------------------------------- 4: live scrape
    import json as _json
    import urllib.request

    sentinel = HealthSentinel(registry=registry, sink=null_sink)
    sentinel.set_extra("fleet", on_router.health_extra)
    with MetricsServer(registry, health=sentinel.state,
                       slo=slo.state,
                       fleet=on_router.fleet_state) as srv:
        with urllib.request.urlopen(srv.url + "/metrics",
                                    timeout=10) as r:
            prom = r.read().decode()
        with urllib.request.urlopen(srv.url + "/fleet", timeout=10) as r:
            fleet_doc = _json.loads(r.read().decode())
        with urllib.request.urlopen(srv.url + "/healthz",
                                    timeout=10) as r:
            healthz = _json.loads(r.read().decode())
            healthz_code = r.status
        with urllib.request.urlopen(srv.url + "/slo", timeout=10) as r:
            slo_code = r.status
    missing = [f for f in REQUIRED_FAMILIES
               if f'{f}{{' not in prom.replace(" ", "")
               or 'worker="0"' not in prom or 'worker="1"' not in prom]
    report["scrape"] = {
        "families_required": list(REQUIRED_FAMILIES),
        "families_missing": missing,
        "fleet_route_workers": len(fleet_doc.get("workers", [])),
        "fleet_route_conservation":
            fleet_doc.get("conservation", {}).get("frac"),
        "healthz_status": healthz.get("status"),
        "healthz_fleet_workers": len(
            (healthz.get("fleet") or {}).get("workers", [])),
        "healthz_code": healthz_code,
        "slo_code": slo_code,
        "ok": bool(not missing
                   and len(fleet_doc.get("workers", [])) == 2
                   and healthz_code == 200
                   and len((healthz.get("fleet") or {})
                           .get("workers", [])) == 2),
    }

    # stop the A/B fleet (poison pill flushes the worker trace shards)
    on_router.stop()
    off_router.stop()
    tracer.save(trace_path)

    # ------------------------------------------- trace stitch
    import json as _json2

    with open(trace_path) as f:
        parent = _json2.load(f)
    shards = discover_shards(trace_path)
    shard_events, shard_infos = stitch_shards(
        parent.get("otherData", {}), shards)
    stitched = parent["traceEvents"] + shard_events
    summary = summarize([e for e in stitched
                         if isinstance(e, dict)],
                        parent.get("otherData", {}))
    pf = summary.get("proc_flows") or {}
    report["trace_stitch"] = {
        "shards": shard_infos,
        "proc_flows": pf,
        "ok": bool(len(shard_infos) == 2
                   and pf.get("starts", 0) > 0
                   and pf.get("steps", 0) > 0
                   and pf.get("finishes", 0) > 0),
    }

    # ------------------------------------------- 5: chaos postmortem
    import signal

    chaos_router = _mk_router(ProcessRouter, telemetry=True,
                              delay_s=0.2, slots=16)
    with chaos_router:
        img = images[0]
        chaos_router.submit(img).result(timeout=60)
        pid0 = chaos_router.workers[0].worker_stats()["pid"]
        futs = [chaos_router.submit(img) for _ in range(6)]
        time.sleep(0.05)                       # land the kill MID-batch
        os.kill(pid0, signal.SIGKILL)
        resolved = {"ok": 0, "error": 0}
        for f in futs:
            try:
                f.result(timeout=60)
                resolved["ok"] += 1
            except Exception:  # noqa: BLE001 — typed = resolved
                resolved["error"] += 1
        deadline = time.perf_counter() + 10
        while (chaos_router.workers[0].last_postmortem is None
               and time.perf_counter() < deadline):
            time.sleep(0.02)
        pm = chaos_router.workers[0].last_postmortem
    pm_ok, pm_problems = (verify_postmortem(pm) if pm is not None
                          else (False, ["no postmortem exhumed"]))
    report["chaos"] = {
        "injection": "SIGKILL worker 0 mid-batch",
        "killed_pid": pid0,
        "futures_resolved": resolved,
        "postmortem_ok": pm_ok,
        "postmortem_problems": pm_problems,
        "postmortem": pm,
        "ok": bool(pm_ok
                   and sum(resolved.values()) == len(futs)),
    }

    set_sink(null_sink)
    set_tracer(null_tracer)
    sink.close()
    if not args.keep_workdir:
        shutil.rmtree(workdir, ignore_errors=True)
    else:
        report["workdir"] = workdir

    report["ok"] = bool(all(report[k]["ok"] for k in
                            ("overhead", "conservation", "compiles",
                             "scrape", "trace_stitch", "chaos")))
    return report


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=6,
                    help="interleaved A/B round pairs")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=50,
                    help="closed-loop requests per client per round")
    ap.add_argument("--quick", action="store_true",
                    help="CI budget: 3 rounds x 20 requests")
    ap.add_argument("--keep-workdir", action="store_true",
                    help="keep the trace/sink workdir for inspection")
    ap.add_argument("--out", default="FLEET_OBS.json")
    args = ap.parse_args()
    if args.quick:
        args.rounds, args.requests = 3, 20

    report = audit(args)

    from improved_body_parts_tpu.obs.events import strict_dump

    with open(args.out, "w") as f:
        strict_dump(report, f, indent=2, sort_keys=True)
    ov = report["overhead"]
    print(f"overhead: median {ov['paired_median_overhead_pct']}% "
          f"(gate < {ov['gate_pct']}%) "
          f"{'OK' if ov['ok'] else 'FAIL'}")
    print(f"conservation: frac {report['conservation']['frac']} "
          f"(gate >= {report['conservation']['gate']}) "
          f"{'OK' if report['conservation']['ok'] else 'FAIL'}")
    print(f"compiles: {report['compiles']['parent_per_arm_delta']} "
          f"{'OK' if report['compiles']['ok'] else 'FAIL'}")
    print(f"scrape: missing={report['scrape']['families_missing']} "
          f"{'OK' if report['scrape']['ok'] else 'FAIL'}")
    print(f"trace stitch: {report['trace_stitch']['proc_flows']} "
          f"{'OK' if report['trace_stitch']['ok'] else 'FAIL'}")
    print(f"chaos: postmortem_ok={report['chaos']['postmortem_ok']} "
          f"{'OK' if report['chaos']['ok'] else 'FAIL'}")
    print(f"wrote {args.out}  overall: "
          f"{'OK' if report['ok'] else 'FAIL'}")
    if not report["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
