#!/usr/bin/env python
"""Test-time-augmentation grid comparison on one checkpoint, plus the
fused-vs-looped TTA dispatch A/B (``--ab``).

Grid mode evaluates the same model + val set under several inference
grids — the reference's TTA surface (reference: evaluate.py:87-96:
``scale_search`` × rotation grid × flip ensemble; ``utils/config:14``
ships scale_search=1 as the default protocol) — and writes one JSON
artifact with AP + wall time per grid, so "does this grid pay on this
data?" is a measured row instead of a plumbing claim.  Round 4 measured
these grids with scratch scripts (TTA_SYNTH.json); this is the
committed tool.

    python tools/tta_bench.py --config synth_deep --checkpoint ckpt/epoch_N \
        --anno person_keypoints.json --images-dir val/ --out TTA.json

Grids: single (scale 1, no rotation — the default protocol), rot±30
(the reference's hard-pose rotation ensemble), rot±60 (covers the hard
synthetic tier's ±60° figure rotations), ms (0.8/1.0/1.2 multi-scale),
and ms×rot±60 (the full 15-lane product grid the reference's TTA
surface spans).  All run device-resident through the compact ms path.

``--ab`` runs the ISSUE 20 dispatch A/B instead (no checkpoint / val
set needed — synthetic images over a planted model): the looped path
runs one jitted program per (scale, rotation) grid entry plus an
averaging program — ``n_entries + 1`` dispatches per image — while the
fused path (``Predictor._fused_grid_fn``) folds every scale's forward,
every rotation lane, the flip merge, the regrid-resize and the compact
extraction into ONE jitted ensemble program: one dispatch, one packed
~100 KB round-trip per image.  The payloads are BIT-identical (the
fused program is the same computation graph re-associated, not an
approximation) — the A/B gates that, then measures what the dispatch
collapse is worth.

Verdict protocol (the standing ROADMAP bench discipline): rounds
interleave a fused arm and a looped arm over the SAME images, so slow
host drift hits both arms of a round equally; the verdict is the median
per-round ``looped_ms / fused_ms`` ratio.  Post-warmup recompiles are
counted per arm by the obs CompileWatch and must be 0.  Gates written
into TTA_AB.json: bitwise payload equality on every image, OKS
synthetic-AP parity of the decoded people exactly 1.0, median fused
dispatches/image == 1, speedup >= ``--gate``, 0 recompiles/arm.

    python tools/tta_bench.py --ab --config tiny --size 128 \
        --boxsize 128 --scales 0.5,0.75,1.0 --rotations 0,30,-30 \
        --out TTA_AB.json
"""
import argparse
import dataclasses
import os
import sys
import tempfile
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))
sys.path.insert(0, _TOOLS)  # for `from evaluate import load_predictor`

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


GRIDS = {
    "single_scale": {},
    "rotation_pm30": {"rotation_search": (0.0, 30.0, -30.0)},
    # the hard synthetic tier rotates figures up to ±60° — a ±30 grid
    # cannot cover it; the reference's rotation search takes arbitrary
    # angle lists (reference: evaluate.py:89-90)
    "rotation_pm60": {"rotation_search": (0.0, 30.0, -30.0, 60.0, -60.0)},
    "multi_scale": {"scale_search": (0.8, 1.0, 1.2)},
    # the full product grid the reference's TTA surface spans
    "ms_rot_pm60": {"scale_search": (0.8, 1.0, 1.2),
                    "rotation_search": (0.0, 30.0, -30.0, 60.0, -60.0)},
}


# ------------------------------------------------ fused-vs-looped A/B


def run_arm(pred, images, prm, fused):
    """One timed arm slice: every image through the ms dispatch with
    the payload fetched to the host (the full round-trip the serving
    path pays).  Returns per-image latencies + dispatch counts."""
    import numpy as np

    lat, dispatches = [], []
    for img in images:
        d0 = pred.dispatch_count
        t0 = time.perf_counter()
        packed_d, _, _ = pred._compact_ms_dispatch(img, None, prm,
                                                   fused=fused)
        np.asarray(packed_d)  # block: the payload crosses the boundary
        lat.append((time.perf_counter() - t0) * 1e3)
        dispatches.append(pred.dispatch_count - d0)
    return lat, dispatches


def arm_summary(lat, dispatches, recompile_delta):
    import numpy as np

    return {
        "images": len(lat),
        "total_ms": round(float(np.sum(lat)), 3),
        "p50_ms": round(float(np.median(lat)), 3),
        "mean_ms": round(float(np.mean(lat)), 3),
        "dispatches_per_image": dispatches,
        "median_dispatches_per_image": float(np.median(dispatches)),
        "recompile_delta": recompile_delta,
    }


def oks_ap(ref_people, det_people):
    """OKS-matched AP of one arm's decoded people against the other's
    over the COCO threshold ladder (stream_bench's SyntheticAP
    matching, with the looped arm standing as ground truth): bit-equal
    payloads score exactly 1.0."""
    import numpy as np

    from improved_body_parts_tpu.stream.track import (
        _extent_area, _to_arrays, greedy_match, keypoint_similarity)

    thresholds = tuple(round(0.5 + 0.05 * i, 2) for i in range(10))
    tp = {t: 0 for t in thresholds}
    denom = 0
    for refs_raw, dets_raw in zip(ref_people, det_people):
        refs = [_to_arrays(kp) for kp, _ in refs_raw]
        dets = [_to_arrays(kp) for kp, _ in dets_raw]
        sim = np.zeros((len(refs), len(dets)), dtype=np.float64)
        for gi, (gxy, gvalid) in enumerate(refs):
            area = _extent_area(gxy, gvalid)
            for di, (dxy, dvalid) in enumerate(dets):
                sim[gi, di] = keypoint_similarity(gxy, gvalid, dxy,
                                                  dvalid, area=area)
        matched = [sim[gi, di] for gi, di in greedy_match(sim, 1e-6)]
        for t in thresholds:
            tp[t] += sum(1 for s in matched if s >= t)
        denom += max(len(refs), len(dets))
    if denom == 0:
        return 1.0
    return float(sum(tp[t] / denom for t in thresholds)) / len(thresholds)


def ab_main(args):
    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    platform = devices_with_timeout(900)[0].platform
    print(f"platform={platform}", flush=True)

    from e2e_bench import PlantedModel, planted_maps, synth_images

    from improved_body_parts_tpu.config import (
        InferenceModelParams, default_inference_params, get_config)
    from improved_body_parts_tpu.infer.decode import decode_compact
    from improved_body_parts_tpu.infer.predict import Predictor
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs import Registry, RunTelemetry
    from improved_body_parts_tpu.utils.precision import apply_serve_dtype

    scales = tuple(float(s) for s in args.scales.split(","))
    rotations = tuple(float(r) for r in args.rotations.split(","))
    n_entries = len(scales) * len(rotations)

    cfg = get_config(args.config)
    model = build_model(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, args.size, args.size, 3)),
                           train=False)
    model, variables = apply_serve_dtype(args.params_dtype, model,
                                         variables)
    rng = np.random.default_rng(0)
    if args.planted > 0:
        canvas = max(int(args.boxsize / 0.6) + 64, 256)
        model = PlantedModel(model, planted_maps(cfg.skeleton,
                                                 args.planted, rng,
                                                 canvas=canvas),
                             cfg.skeleton)
    pred = Predictor(model, variables, cfg.skeleton,
                     model_params=InferenceModelParams(
                         boxsize=args.boxsize))
    base, _ = default_inference_params()
    prm = dataclasses.replace(base, scale_search=scales,
                              rotation_search=rotations)
    images = synth_images(args.num_images, args.size,
                          np.random.default_rng(1))

    sink_path = None
    if args.telemetry_sink not in ("none", ""):
        sink_path = (os.path.splitext(args.out)[0] + "_events.jsonl"
                     if args.telemetry_sink == "auto"
                     else args.telemetry_sink)
    telemetry = RunTelemetry(
        sink_path, registry=Registry(),
        run_meta={"tool": "tta_bench_ab", "config": args.config,
                  "platform": platform})

    report = {
        "platform": platform, "config": args.config,
        "images": args.num_images, "size": args.size,
        "boxsize": args.boxsize, "planted_people": args.planted,
        "scale_search": list(scales),
        "rotation_search": list(rotations),
        "grid_entries": n_entries, "rounds": args.rounds,
        "params_dtype": args.params_dtype,
        "telemetry_events": sink_path,
        "note": "rounds interleave a fused arm (ONE ensemble program "
                "per image) and a looped arm (one program per grid "
                "entry + the averaging program) over the same images, "
                "so host drift hits both equally (ROADMAP standing "
                "protocol: absolute ms on a shared-core CPU host is "
                "noise — the per-round ratio, the dispatch counts, the "
                "bitwise payload gate and the recompile verdicts are "
                "the signal).  The speedup gate BINDS on accelerator "
                "platforms only: on the CPU backend the looped arm's "
                "per-entry programs overlap across host cores (the "
                "async-dispatch client runs whole executables "
                "concurrently), a parallelism a single chip's serial "
                "program queue does not offer — on TPU every looped "
                "entry pays a full dispatch + round-trip latency in "
                "series, which is exactly what the fused program "
                "collapses (same class of win as the fused decode's "
                "PERF_AUDIT_B on-chip rows).",
    }

    def flush():
        with open(args.out, "w") as f:
            strict_dump(report, f, indent=2)

    # ---- payload + AP parity gates (untimed; doubles as warmup) ----
    payload_equal = True
    fused_people, looped_people = [], []
    for img in images:
        pf, rh0, cs = pred._compact_ms_dispatch(img, None, prm,
                                                fused=True)
        pl, _, _ = pred._compact_ms_dispatch(img, None, prm,
                                             fused=False)
        a, b = np.asarray(pf), np.asarray(pl)
        payload_equal &= bool((a == b).all())
        rf = pred._unpack_compact(a, pred.compact_topk, rh0, cs)
        rl = pred._unpack_compact(b, pred.compact_topk, rh0, cs)
        fused_people.append(decode_compact(rf, prm, pred.skeleton))
        looped_people.append(decode_compact(rl, prm, pred.skeleton))
    ap_val = oks_ap(looped_people, fused_people)
    report["payload_equal_all_images"] = payload_equal
    report["ap_parity"] = {
        "fused_vs_looped_oks_ap": round(ap_val, 6),
        "people_per_image": [len(p) for p in looped_people],
        "equal": bool(ap_val == 1.0),
    }
    print(f"payload equal: {payload_equal}; AP parity {ap_val}",
          flush=True)
    telemetry.mark_warm("parity gates ran both arms over every image")
    watch = telemetry.compile_watch

    rounds = []
    for r in range(args.rounds):
        c0 = int(watch.recompiles.value)
        lat_f, disp_f = run_arm(pred, images, prm, fused=True)
        fused = arm_summary(lat_f, disp_f,
                            int(watch.recompiles.value) - c0)
        c0 = int(watch.recompiles.value)
        lat_l, disp_l = run_arm(pred, images, prm, fused=False)
        looped = arm_summary(lat_l, disp_l,
                             int(watch.recompiles.value) - c0)
        rounds.append({"fused": fused, "looped": looped})
        report["rounds_detail"] = rounds
        flush()
        telemetry.emit("tta_ab_round", round=r,
                       fused_total_ms=fused["total_ms"],
                       looped_total_ms=looped["total_ms"])
        print(f"round {r}: fused {fused['total_ms']} ms "
              f"({fused['median_dispatches_per_image']:.0f} dispatch/"
              f"img) vs looped {looped['total_ms']} ms "
              f"({looped['median_dispatches_per_image']:.0f})",
              flush=True)

    ratios = sorted(r["looped"]["total_ms"]
                    / max(r["fused"]["total_ms"], 1e-9) for r in rounds)
    report["per_round_fused_speedup"] = [round(x, 3) for x in ratios]
    report["median_fused_speedup"] = round(ratios[len(ratios) // 2], 3)
    report["fused_speedup_gate"] = args.gate
    report["fused_speedup_gate_binding"] = platform != "cpu"
    report["fused_speedup_sustained"] = bool(
        report["median_fused_speedup"] >= args.gate)
    report["median_fused_dispatches_per_image"] = float(np.median(
        [d for r in rounds for d in r["fused"]["dispatches_per_image"]]))
    report["median_looped_dispatches_per_image"] = float(np.median(
        [d for r in rounds
         for d in r["looped"]["dispatches_per_image"]]))
    report["fused_arm_recompile_delta_total"] = sum(
        r["fused"]["recompile_delta"] for r in rounds)
    report["looped_arm_recompile_delta_total"] = sum(
        r["looped"]["recompile_delta"] for r in rounds)
    report["recompiles_post_warmup"] = int(watch.recompiles.value)
    verdict = {
        "payload_equal_all_images": payload_equal,
        "ap_parity_equal": report["ap_parity"]["equal"],
        "median_fused_speedup": report["median_fused_speedup"],
        "fused_speedup_sustained": report["fused_speedup_sustained"],
        "median_fused_dispatches_per_image":
            report["median_fused_dispatches_per_image"],
        "recompiles_post_warmup": report["recompiles_post_warmup"],
    }
    telemetry.emit("tta_ab_verdict", **verdict)
    telemetry.close()
    flush()
    print(strict_dumps(verdict))
    ok = (payload_equal and report["ap_parity"]["equal"]
          and report["median_fused_dispatches_per_image"] == 1.0
          and report["recompiles_post_warmup"] == 0
          and (report["fused_speedup_sustained"]
               or not report["fused_speedup_gate_binding"]))
    sys.exit(0 if ok else 1)


def main():
    ap = argparse.ArgumentParser(description="TTA grid comparison / "
                                             "fused-dispatch A/B")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--anno", default=None)
    ap.add_argument("--images-dir", "--images", dest="images_dir",
                    default=None, help="val image directory (grid mode)")
    ap.add_argument("--max-images", type=int, default=500)
    ap.add_argument("--boxsize", type=int, default=0)
    ap.add_argument("--grids", nargs="+", default=list(GRIDS),
                    choices=list(GRIDS))
    ap.add_argument("--out", default="TTA.json")
    ap.add_argument("--results-dir", default=None,
                    help="where the per-grid detection dumps land; "
                         "default: a temp dir (NOT ./results — running "
                         "from the checkout must not pollute it)")
    ap.add_argument("--no-native", action="store_true")
    # ------------------------------------------- fused-vs-looped A/B
    ap.add_argument("--ab", action="store_true",
                    help="run the fused-vs-looped TTA dispatch A/B "
                         "(synthetic planted protocol, no checkpoint/"
                         "val set; writes the verdict artifact to "
                         "--out, default TTA_AB.json)")
    ap.add_argument("--num-images", type=int, default=6,
                    help="A/B: bench images per arm per round")
    ap.add_argument("--size", type=int, default=128,
                    help="A/B: square input image size")
    ap.add_argument("--scales", default="0.5,0.75,1.0",
                    help="A/B: comma-separated scale_search grid")
    ap.add_argument("--rotations", default="0,30,-30",
                    help="A/B: comma-separated rotation_search grid")
    ap.add_argument("--rounds", type=int, default=5,
                    help="A/B: interleaved fused/looped verdict rounds")
    ap.add_argument("--gate", type=float, default=1.3,
                    help="A/B: median per-round speedup the fused arm "
                         "must sustain")
    ap.add_argument("--planted", type=int, default=2,
                    help="A/B: plant GT-style maps for N synthetic "
                         "people (decodable payloads for the AP-parity "
                         "gate)")
    ap.add_argument("--params-dtype", default="auto",
                    choices=["auto", "bf16", "fp32", "int8"])
    ap.add_argument("--telemetry-sink", default="auto",
                    help="A/B: JSONL event stream ('auto' = "
                         "<out>_events.jsonl, 'none' disables)")
    args = ap.parse_args()

    if args.ab:
        if args.out == "TTA.json":
            args.out = "TTA_AB.json"
        if args.boxsize == 0:
            args.boxsize = args.size
        if args.rounds < 1:
            ap.error("--rounds must be >= 1")
        ab_main(args)
        return
    for flag in ("checkpoint", "anno", "images_dir"):
        if getattr(args, flag) is None:
            ap.error(f"--{flag.replace('_', '-')} is required in grid "
                     "mode (or pass --ab)")

    from evaluate import load_predictor

    from improved_body_parts_tpu.config import default_inference_params
    from improved_body_parts_tpu.infer.evaluate import validation_oks

    predictor = load_predictor(args.config, args.checkpoint,
                               boxsize=args.boxsize)
    results_dir = args.results_dir or tempfile.mkdtemp(prefix="tta_results_")
    base, _ = default_inference_params()
    results = {}
    for name in args.grids:
        params = dataclasses.replace(base, **GRIDS[name])
        t0 = time.time()
        metrics = validation_oks(
            predictor, args.anno, args.images_dir,
            max_images=args.max_images,
            params=params, use_native=not args.no_native, compact=True,
            dump_name=f"tta_{name}", results_dir=results_dir)
        entry = {k: metrics[k] for k in ("AP", "AP50", "AP75", "AR")}
        entry["seconds"] = round(time.time() - t0, 1)
        for k, v in GRIDS[name].items():
            entry[k] = list(v)
        results[name] = entry
        print(f"{name}: AP={metrics['AP']:.4f} ({entry['seconds']}s)",
              flush=True)

    out = {"config": args.config, "checkpoint": args.checkpoint,
           "val": args.images_dir,
           "decode_path": "compact (device-resident grid)",
           "grids": results}
    with open(args.out, "w") as f:
        strict_dump(out, f, indent=2)
    print(strict_dumps({k: v["AP"] for k, v in results.items()}))


if __name__ == "__main__":
    main()
