#!/usr/bin/env python
"""Test-time-augmentation grid comparison on one checkpoint.

Evaluates the same model + val set under several inference grids — the
reference's TTA surface (reference: evaluate.py:87-96: ``scale_search`` ×
rotation grid × flip ensemble; ``utils/config:14`` ships scale_search=1
as the default protocol) — and writes one JSON artifact with AP + wall
time per grid, so "does this grid pay on this data?" is a measured row
instead of a plumbing claim.  Round 4 measured these grids with scratch
scripts (TTA_SYNTH.json); this is the committed tool.

    python tools/tta_bench.py --config synth_deep --checkpoint ckpt/epoch_N \
        --anno person_keypoints.json --images val/ --out TTA.json

Grids: single (scale 1, no rotation — the default protocol), rot±30
(the reference's hard-pose rotation ensemble), rot±60 (covers the hard
synthetic tier's ±60° figure rotations), ms (0.8/1.0/1.2 multi-scale),
and ms×rot±60 (the full 15-lane product grid the reference's TTA
surface spans).  All run device-resident through the compact ms path.
"""
import argparse
import dataclasses
import json
import os
import sys
import tempfile
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))
sys.path.insert(0, _TOOLS)  # for `from evaluate import load_predictor`

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


GRIDS = {
    "single_scale": {},
    "rotation_pm30": {"rotation_search": (0.0, 30.0, -30.0)},
    # the hard synthetic tier rotates figures up to ±60° — a ±30 grid
    # cannot cover it; the reference's rotation search takes arbitrary
    # angle lists (reference: evaluate.py:89-90)
    "rotation_pm60": {"rotation_search": (0.0, 30.0, -30.0, 60.0, -60.0)},
    "multi_scale": {"scale_search": (0.8, 1.0, 1.2)},
    # the full product grid the reference's TTA surface spans
    "ms_rot_pm60": {"scale_search": (0.8, 1.0, 1.2),
                    "rotation_search": (0.0, 30.0, -30.0, 60.0, -60.0)},
}


def main():
    ap = argparse.ArgumentParser(description="TTA grid comparison")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--anno", required=True)
    ap.add_argument("--images", required=True)
    ap.add_argument("--max-images", type=int, default=500)
    ap.add_argument("--boxsize", type=int, default=0)
    ap.add_argument("--grids", nargs="+", default=list(GRIDS),
                    choices=list(GRIDS))
    ap.add_argument("--out", default="TTA.json")
    ap.add_argument("--results-dir", default=None,
                    help="where the per-grid detection dumps land; "
                         "default: a temp dir (NOT ./results — running "
                         "from the checkout must not pollute it)")
    ap.add_argument("--no-native", action="store_true")
    args = ap.parse_args()

    from evaluate import load_predictor

    from improved_body_parts_tpu.config import default_inference_params
    from improved_body_parts_tpu.infer.evaluate import validation_oks

    predictor = load_predictor(args.config, args.checkpoint,
                               boxsize=args.boxsize)
    results_dir = args.results_dir or tempfile.mkdtemp(prefix="tta_results_")
    base, _ = default_inference_params()
    results = {}
    for name in args.grids:
        params = dataclasses.replace(base, **GRIDS[name])
        t0 = time.time()
        metrics = validation_oks(
            predictor, args.anno, args.images, max_images=args.max_images,
            params=params, use_native=not args.no_native, compact=True,
            dump_name=f"tta_{name}", results_dir=results_dir)
        entry = {k: metrics[k] for k in ("AP", "AP50", "AP75", "AR")}
        entry["seconds"] = round(time.time() - t0, 1)
        for k, v in GRIDS[name].items():
            entry[k] = list(v)
        results[name] = entry
        print(f"{name}: AP={metrics['AP']:.4f} ({entry['seconds']}s)",
              flush=True)

    out = {"config": args.config, "checkpoint": args.checkpoint,
           "val": args.images,
           "decode_path": "compact (device-resident grid)",
           "grids": results}
    with open(args.out, "w") as f:
        strict_dump(out, f, indent=2)
    print(strict_dumps({k: v["AP"] for k, v in results.items()}))


if __name__ == "__main__":
    main()
