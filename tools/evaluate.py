#!/usr/bin/env python
"""COCO evaluation CLI (reference: evaluate.py __main__, :625-650).

    python tools/evaluate.py --checkpoint checkpoints/epoch_99 \
        --anno annotations/person_keypoints_val2017.json --images val2017
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_predictor(config_name: str, checkpoint: str, bucket: int = 128,
                   boxsize: int = 0, params_dtype: str = "auto"):
    import jax
    import jax.numpy as jnp

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()  # honour JAX_PLATFORMS even under a sitecustomize

    from improved_body_parts_tpu.config import (
        InferenceModelParams, get_config)
    from improved_body_parts_tpu.infer import Predictor
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.train import restore_checkpoint
    from improved_body_parts_tpu.utils.precision import apply_serve_dtype

    cfg = get_config(config_name)
    model = build_model(cfg)
    payload = restore_checkpoint(checkpoint)
    model, variables = apply_serve_dtype(
        params_dtype, model, {"params": payload["params"],
                              "batch_stats": payload["batch_stats"]})
    model_params = InferenceModelParams(boxsize=boxsize) if boxsize else None
    return Predictor(model, variables, cfg.skeleton, bucket=bucket,
                     model_params=model_params)


def main():
    ap = argparse.ArgumentParser(description="COCO keypoint evaluation")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--anno", required=True,
                    help="person_keypoints_val2017.json")
    ap.add_argument("--images", required=True, help="val2017 image dir")
    ap.add_argument("--max-images", type=int, default=500,
                    help="first-N protocol (reference: evaluate.py:597-598)")
    ap.add_argument("--dump-name", default="tpu")
    ap.add_argument("--no-native", action="store_true",
                    help="use the NumPy decoder instead of the C++ one")
    ap.add_argument("--fast", action="store_true",
                    help="single-scale fast path: on-device NMS, decode at "
                         "network resolution")
    ap.add_argument("--compact", action="store_true",
                    help="single-scale compact path: peak extraction + limb "
                         "pair scoring on-device, ~100 KB/image transfer")
    ap.add_argument("--compact-batch", type=int, default=0,
                    help="throughput mode: N images + mirrors per dispatch, "
                         "shape-bucketed (implies the compact path)")
    ap.add_argument("--device-decode", action="store_true",
                    help="fused end-to-end decode: greedy person assembly "
                         "runs ON DEVICE in the same program as the "
                         "forward (implies the compact path; overflowing "
                         "crowds fall back to the host decoder)")
    ap.add_argument("--boxsize", type=int, default=0,
                    help="scale val images so their height maps to this "
                         "network input size (the reference's INI "
                         "[models] boxsize, utils/config:37-41); 0 keeps "
                         "the library default")
    ap.add_argument("--params-dtype", default="auto",
                    choices=["auto", "bf16", "fp32", "int8"],
                    help="inference weight storage; auto = bf16 on TPU "
                         "(halves weight HBM traffic, PERF_AUDIT_BF16.json; "
                         "matches the reference's AMP-O1 eval), fp32 "
                         "elsewhere; int8 = weight-only per-channel "
                         "quantization with in-program dequant "
                         "(utils.precision.quantize_int8)")
    ap.add_argument("--oks-proxy", action="store_true",
                    help="evaluate with the dependency-free OKS evaluator "
                         "(COCOeval ignore/crowd/maxDets semantics, "
                         "APCHECK.md) instead of pycocotools")
    args = ap.parse_args()

    from improved_body_parts_tpu.infer.evaluate import (
        validation, validation_oks)

    use_proxy = args.oks_proxy
    if not use_proxy:
        try:
            # probe the compiled modules validation actually needs, not the
            # (possibly empty/broken) top-level package
            from pycocotools.cocoeval import COCOeval  # noqa: F401
        except ImportError:
            print("pycocotools not usable — falling back to the OKS "
                  "proxy evaluator (--oks-proxy)")
            use_proxy = True

    predictor = load_predictor(args.config, args.checkpoint,
                               boxsize=args.boxsize,
                               params_dtype=args.params_dtype)
    if use_proxy:
        metrics = validation_oks(predictor, args.anno, args.images,
                                 max_images=args.max_images,
                                 use_native=not args.no_native,
                                 fast=args.fast, compact=args.compact,
                                 compact_batch=args.compact_batch,
                                 device_decode=args.device_decode,
                                 dump_name=args.dump_name)
        print("AP:", metrics["AP"])
    else:
        coco_eval = validation(predictor, args.anno, args.images,
                               dump_name=args.dump_name,
                               max_images=args.max_images,
                               use_native=not args.no_native,
                               fast=args.fast, compact=args.compact,
                               compact_batch=args.compact_batch,
                               device_decode=args.device_decode)
        print("AP:", coco_eval.stats[0])


if __name__ == "__main__":
    main()
