#!/usr/bin/env python
"""SWA fine-tuning stage on a synth_ap workdir, with before/after AP.

Continues a completed ``tools/synth_ap.py --keep-workdir`` run through
the reference's SWA protocol — cyclic LR, frozen BN, averaged-swap
checkpoints (reference: train_distributed_SWA.py) — then evaluates the
averaged weights on the SAME held-out val set and writes one artifact
with ap_base / ap_swa / delta.  This is the committed pipeline behind
the SYNTH_AP_DEEP_SWA_S<seed>.json artifacts that tools/ab_summary.py
aggregates.

    python tools/synth_ap.py --config synth_deep --seed 1 ... \
        --workdir WORK --keep-workdir --out SYNTH_AP_DEEP_S1.json
    python tools/swa_stage.py --workdir WORK --base SYNTH_AP_DEEP_S1.json \
        --out SYNTH_AP_DEEP_SWA_S1.json
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

# one parser/runner for the train/evaluate CLI output format, shared with
# the base-run orchestrator
from synth_ap import parse_ap, run_cli  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", required=True,
                    help="a synth_ap --keep-workdir directory (train_drawn"
                         ".h5, ckpt/, val/, person_keypoints_synth.json)")
    ap.add_argument("--config", default="synth_deep")
    ap.add_argument("--base", default=None,
                    help="the base run's artifact JSON; its ap_trained "
                         "becomes ap_base in the output")
    ap.add_argument("--epochs", type=int, default=5,
                    help="ADDITIONAL SWA epochs (one --swa-freq cycle by "
                         "default)")
    ap.add_argument("--swa-freq", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--boxsize", type=int, default=0,
                    help="0 = the config's input height (synth protocol)")
    ap.add_argument("--out", default="SYNTH_AP_SWA.json")
    args = ap.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    work = os.path.abspath(args.workdir)
    ckpt_dir = os.path.join(work, "ckpt")
    anno = os.path.join(work, "person_keypoints_synth.json")
    val_dir = os.path.join(work, "val")
    h5 = os.path.join(work, "train_drawn.h5")
    for path in (ckpt_dir, anno, val_dir, h5):
        assert os.path.exists(path), f"not a synth_ap workdir: {path} missing"

    if not args.boxsize:
        from improved_body_parts_tpu.config import get_config

        args.boxsize = get_config(args.config).skeleton.height

    print(f"SWA stage: +{args.epochs} epochs on {ckpt_dir}", flush=True)
    run_cli([os.path.join(REPO, "tools", "train.py"), "--config",
             args.config, "--swa", "--resume", "auto",
             "--epochs", str(args.epochs), "--swa-freq", str(args.swa_freq),
             "--train-h5", h5, "--checkpoint-dir", ckpt_dir,
             "--workers", "0", "--seed", str(args.seed)], timeout=21600)

    from improved_body_parts_tpu.train.checkpoint import (latest_checkpoint,
                                                          read_commit_meta)

    # latest_checkpoint only returns COMMITTED checkpoints now — a stage
    # killed mid-write can no longer hand a partial directory to the eval
    latest = latest_checkpoint(ckpt_dir)
    assert latest, f"no checkpoint under {ckpt_dir} after the SWA stage"
    ckpt_meta = read_commit_meta(latest)
    print(f"evaluating SWA checkpoint {latest}", flush=True)
    out = run_cli([os.path.join(REPO, "tools", "evaluate.py"), "--config",
                   args.config, "--checkpoint", latest, "--anno", anno,
                   "--images", val_dir, "--boxsize", str(args.boxsize),
                   "--compact", "--oks-proxy", "--dump-name", "swa"],
                  cwd=work)
    ap_swa = parse_ap(out)

    result = {"config": args.config, "seed": args.seed,
              "swa_epochs": args.epochs, "swa_freq": args.swa_freq,
              "ap_swa": ap_swa, "checkpoint": latest,
              # checkpoint provenance from the commit marker (None for a
              # pre-marker legacy dir): which epoch/metric the evaluated
              # weights actually carry
              "checkpoint_meta": ({k: ckpt_meta[k] for k in
                                   ("epoch", "train_loss", "metric",
                                    "metric_value") if k in ckpt_meta}
                                  if ckpt_meta else None),
              "protocol": "tools/train.py --swa --resume auto (cyclic LR "
                          "1e-5->1e-6, frozen BN, averaged swap) -> "
                          "tools/evaluate.py --compact --oks-proxy on the "
                          "workdir's held-out val"}
    if args.base:
        with open(args.base) as f:
            base = json.load(f)
        result["ap_base"] = base["ap_trained"]
        result["base_artifact"] = os.path.basename(args.base)
        result["swa_delta"] = round(ap_swa - base["ap_trained"], 6)
    with open(args.out, "w") as f:
        strict_dump(result, f, indent=2)
    print(strict_dumps(result))


if __name__ == "__main__":
    main()
