#!/usr/bin/env python
"""Render a run's telemetry history: load curves, trends, gap table —
with a shard completeness verifier (``--strict`` exits nonzero).

Reads the ``*_history.jsonl`` (+ ``.pN``) shards a
``obs.history.HistoryStore`` persisted, replays them offline and
renders:

- a **verifier block** first — an incomplete or inconsistent stream
  must be impossible to mistake for a healthy one: every shard parses,
  headers agree (run_id / cadence / schema), shard numbers are
  contiguous, sample times are strictly increasing, every sampled key
  was declared, and the persisted ``history_gap`` records match what
  re-detection over the tick spacing finds (count for count — a gap
  that was detected but not persisted, or persisted but not
  re-detectable, is an accounting break);
- per-series **load curves** (text bars over the raw ring) and the
  window **trend** (slope/s) for the requested series (default: the
  control-plane signal set that is actually present);
- the **gap table**: every sampler blackout with its span and missed
  tick estimate.

    python tools/history_report.py runs/events_history.jsonl
    python tools/history_report.py runs/events_history.jsonl \\
        --series serve_queue_depth --window 30 --strict
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: default series to render when none are named — the ROADMAP item 1
#: signal set (whatever subset the stream actually carries)
DEFAULT_SERIES = (
    "serve_queue_depth",
    "pool_queue_depth",
    "serve_completed_total",
    "serve_hop_conservation_frac",
)

CURVE_WIDTH = 48
CURVE_ROWS = 24


def verify_history(path):
    """Structural + accounting verification of one history stream.
    Returns ``(ok, problems, stats)``; importable (the tests seed it
    with both healthy and broken streams)."""
    from improved_body_parts_tpu.obs.events import read_events
    from improved_body_parts_tpu.obs.history import (
        HISTORY_SCHEMA, discover_history_shards)

    problems = []
    shards = discover_history_shards(path)
    if not shards:
        return False, [f"no shards found at {path!r}"], {}
    header = None
    declared = set()
    ticks = 0
    persisted_gaps = []
    last_t = None
    redetected = 0
    for i, p in enumerate(shards):
        recs = read_events(p)
        if not recs:
            problems.append(f"{p}: empty shard")
            continue
        first = recs[0]
        if first.get("event") != "history_start":
            problems.append(f"{p}: first record is "
                            f"{first.get('event')!r}, not history_start")
        else:
            if first.get("schema", 0) > HISTORY_SCHEMA:
                problems.append(
                    f"{p}: schema {first.get('schema')} > supported "
                    f"{HISTORY_SCHEMA}")
            if first.get("shard") != i:
                problems.append(
                    f"{p}: header says shard {first.get('shard')}, "
                    f"position says {i} (missing or reordered shard)")
            if header is None:
                header = first
            else:
                for k in ("run_id", "cadence_s", "gap_factor", "levels"):
                    if first.get(k) != header.get(k):
                        problems.append(
                            f"{p}: header {k}={first.get(k)!r} != "
                            f"shard-0 {header.get(k)!r}")
        for rec in recs:
            ev = rec.get("event")
            if ev == "history_series":
                declared.add(rec.get("key"))
            elif ev == "history_gap":
                persisted_gaps.append(rec)
            elif ev == "history_sample":
                t = rec.get("t")
                if not isinstance(t, (int, float)):
                    problems.append(f"{p}: sample without numeric t")
                    continue
                if last_t is not None:
                    if t <= last_t:
                        problems.append(
                            f"{p}: non-increasing t {t} after {last_t}")
                    elif header is not None and (
                            t - last_t > header.get("gap_factor", 2.5)
                            * header.get("cadence_s", 0.25)):
                        redetected += 1
                last_t = t
                ticks += 1
                undeclared = set(rec.get("v", {})) - declared
                if undeclared:
                    problems.append(
                        f"{p}: sampled undeclared series "
                        f"{sorted(undeclared)[:3]}"
                        f"{'…' if len(undeclared) > 3 else ''}")
    if header is None:
        problems.append("no history_start header in any shard")
    if len(persisted_gaps) != redetected:
        problems.append(
            f"gap accounting break: {len(persisted_gaps)} persisted "
            f"history_gap records vs {redetected} re-detected from "
            "tick spacing")
    stats = {
        "shards": len(shards),
        "ticks": ticks,
        "series_declared": len(declared),
        "gaps_persisted": len(persisted_gaps),
        "gaps_redetected": redetected,
        "run_id": header.get("run_id") if header else None,
        "cadence_s": header.get("cadence_s") if header else None,
        "last_t": last_t,
    }
    return not problems, problems, stats


def render_curve(points, width=CURVE_WIDTH, rows=CURVE_ROWS):
    """Text load curve: the last ``rows`` of up-to-``width``-bucketed
    raw points, value-scaled bars."""
    if not points:
        return ["  (no samples)"]
    # thin to at most `rows` lines, newest last
    step = max(1, len(points) // rows)
    pts = points[::step][-rows:]
    vmax = max(abs(v) for _, v in pts) or 1.0
    out = []
    for t, v in pts:
        bar = "#" * max(0, int(round(abs(v) / vmax * width)))
        out.append(f"  t={t:12.3f}  {v:14.6g}  {bar}")
    return out


def summarize(path, series, window_s):
    """Replay the stream and build the render model."""
    from improved_body_parts_tpu.obs.history import HistoryStore

    store = HistoryStore.replay(path)
    doc = store.doc()
    present = [s for s in (series or DEFAULT_SERIES) if s in doc["keys"]]
    missing = [s for s in (series or ()) if s not in doc["keys"]]
    blocks = []
    for key in present:
        q = store.query(key)
        block = {
            "series": key,
            "kind": q["kind"],
            "points": q["points"],
            "latest": store.latest(key),
            "trend": store.trend(key, window_s),
            "quantiles": store.window_quantiles(key, window_s),
        }
        if q["kind"] == "counter":
            block["rate"] = store.rate(key, window_s)
        blocks.append(block)
    return {"doc": doc, "signals": store.signals(),
            "blocks": blocks, "missing": missing}


def render(path, model, verdict, window_s):
    ok, problems, stats = verdict
    lines = [f"history report: {path}", ""]
    lines.append(f"verifier: {'OK' if ok else 'FAIL'} — "
                 f"{stats.get('shards', 0)} shard(s), "
                 f"{stats.get('ticks', 0)} ticks, "
                 f"{stats.get('series_declared', 0)} series, "
                 f"gaps {stats.get('gaps_persisted', 0)} persisted / "
                 f"{stats.get('gaps_redetected', 0)} re-detected, "
                 f"run_id={stats.get('run_id')!r}")
    for p in problems:
        lines.append(f"  !! {p}")
    doc = model["doc"]
    lines.append("")
    lines.append(f"store: cadence {doc['cadence_s']} s, raw ring "
                 f"{doc['raw_capacity']}, levels "
                 f"{['%gs x %d' % (w, c) for w, c in doc['levels']]}, "
                 f"{doc['series']} series, {doc['samples']} samples, "
                 f"last_t {doc['last_t']}")
    sig = model["signals"]
    lines.append(f"signals @ t={sig.get('t')}: queue_depth="
                 f"{sig.get('queue_depth')} admitted="
                 f"{sig.get('admitted_depth')} conservation="
                 f"{sig.get('hop_conservation_frac')} "
                 f"completed_rate={sig.get('completed_rate')}/s")
    for m in model["missing"]:
        lines.append(f"  (requested series {m!r} not in stream)")
    for b in model["blocks"]:
        lines.append("")
        head = f"-- {b['series']} ({b['kind']})"
        if b.get("rate") is not None:
            head += f"  rate[{window_s:g}s]={b['rate']:.6g}/s"
        if b.get("trend") is not None:
            head += f"  trend[{window_s:g}s]={b['trend']:.6g}/s"
        lines.append(head)
        if b.get("quantiles"):
            q = b["quantiles"]
            lines.append("   window quantiles: "
                         + "  ".join(f"{k}={v:.6g}"
                                     for k, v in q.items()))
        lines.extend(render_curve(b["points"]))
    gaps = doc["gaps"]
    lines.append("")
    lines.append(f"gaps: {gaps['count']} "
                 "(sampler blackouts — marked, never interpolated)")
    for g in gaps["recent"]:
        lines.append(f"  {g['t_prev']:.3f} -> {g['t']:.3f}  "
                     f"(~{g['missed']} missed ticks)")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="history stream path "
                                 "(*_history.jsonl; .pN auto-discovered)")
    ap.add_argument("--series", nargs="+", default=None,
                    help="series keys to render "
                         f"(default: {', '.join(DEFAULT_SERIES)})")
    ap.add_argument("--window", type=float, default=30.0,
                    help="window seconds for rate/trend/quantiles")
    ap.add_argument("--json", action="store_true",
                    help="emit the model as strict JSON, not text")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when the verifier finds problems")
    args = ap.parse_args()

    verdict = verify_history(args.path)
    ok, problems, stats = verdict
    if ok or stats.get("ticks"):
        model = summarize(args.path, args.series, args.window)
    else:
        model = {"doc": {}, "signals": {}, "blocks": [], "missing": []}
    if args.json:
        from improved_body_parts_tpu.obs.events import strict_dumps

        print(strict_dumps({"verifier": {"ok": ok, "problems": problems,
                                         **stats}, **model}, indent=2,
                           sort_keys=True, default=str))
    else:
        if model["doc"]:
            print(render(args.path, model, verdict, args.window))
        else:
            print(f"history report: {args.path}")
            print("verifier: FAIL")
            for p in problems:
                print(f"  !! {p}")
    if args.strict and not ok:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
