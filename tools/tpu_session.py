#!/usr/bin/env python
"""One-process TPU measurement session.

The chip behind the axon relay is claimed EXCLUSIVELY at first device use
and a dead claimant can wedge the pool — so when a chip is available, run
everything in ONE process, sequentially, and exit cleanly:

1. single-image 512x512 forward FPS, CHAINED-step timing (the honest
   protocol; reference: test_inference_speed.py:90-120, baseline 38.5),
   plus bf16-param storage;
2. batch sweep (throughput mode — TPUs amortize per-dispatch overhead);
3. Pallas focal kernel parity + timing vs the XLA loss (Mosaic lowering);
4. compact end-to-end (planted 3-person workload): sequential, pipelined,
   and shape-bucketed batch modes (--skip-e2e to skip);
5. train-step timing, state-chained by construction (--skip-train);
6. optional profiler trace for the single-image program.

Writes a JSON summary to --out (default TPURUN.json) and prints progress.

    python tools/tpu_session.py            # full session on the active chip
    JAX_PLATFORMS=cpu python tools/tpu_session.py --quick   # smoke on CPU
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

BACKEND_TIMEOUT_S = 900


def main():
    ap = argparse.ArgumentParser(description="one-process TPU session")
    ap.add_argument("--out", default="TPURUN.json")
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--batches", type=int, nargs="+", default=[1, 2, 4, 8])
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes / few iters (CPU smoke)")
    ap.add_argument("--skip-pallas", action="store_true")
    ap.add_argument("--skip-e2e", action="store_true",
                    help="skip the compact end-to-end section")
    ap.add_argument("--skip-train", action="store_true",
                    help="skip the train-step section")
    ap.add_argument("--e2e-images", type=int, default=16)
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace here")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax

    try:
        devices = devices_with_timeout(60 if args.quick
                                       else BACKEND_TIMEOUT_S)
    except (RuntimeError, TimeoutError) as e:
        raise SystemExit(str(e))
    platform = devices[0].platform
    print(f"platform={platform} devices={len(devices)}", flush=True)

    import jax.numpy as jnp
    import numpy as np

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model

    summary = {"platform": platform, "baseline_fps": 38.5}

    def flush_summary():
        # the chip session is scarce: persist after EVERY section so a late
        # failure never discards earlier measurements
        with open(args.out, "w") as f:
            strict_dump(summary, f, indent=2)
    size = 128 if args.quick else 512
    iters = 3 if args.quick else args.iters
    cfg = get_config("tiny" if args.quick else "canonical")
    model = build_model(cfg)

    from improved_body_parts_tpu.utils import chained_time

    def timed_chained(forward, variables, x, n=iters, warmup=2):
        return chained_time(forward, variables, x, iters=n, warmup=warmup)

    # --- 1. single-image forward (chained = honest latency) --------------
    imgs = jnp.zeros((1, size, size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), imgs, train=False)
    raw_fwd = lambda v, x: model.apply(v, x, train=False)[-1][0]  # noqa: E731
    fwd = jax.jit(raw_fwd)
    print("timing single-image forward (chained steps)...", flush=True)
    dt = timed_chained(raw_fwd, variables, imgs)
    fps = 1.0 / dt
    summary["single_image_fps"] = round(fps, 2)
    summary["vs_baseline"] = round(fps / 38.5, 3)
    flush_summary()
    print(f"single-image {size}x{size} (chained): {fps:.2f} imgs/s "
          f"({dt * 1e3:.2f} ms)", flush=True)

    # --- 1b. bf16 param storage (HBM-traffic lever: fp32 params are
    # ~516 MB/pass of the ~5.7 GB the forward reads; casting storage to
    # bf16 halves weight traffic — measure, don't assume)
    from improved_body_parts_tpu.utils import bf16_params

    bf16_vars = bf16_params(variables)
    dt16 = timed_chained(raw_fwd, bf16_vars, imgs)
    summary["single_image_fps_bf16_params"] = round(1.0 / dt16, 2)
    flush_summary()
    print(f"bf16-param storage: {1.0 / dt16:.2f} imgs/s", flush=True)

    # --- 2. batch sweep (chained) ----------------------------------------
    sweep = {}
    for b in args.batches:
        bi = jnp.zeros((b, size, size, 3), jnp.float32)
        dt = timed_chained(raw_fwd, variables, bi)
        sweep[b] = round(b / dt, 2)
        print(f"batch {b}: {sweep[b]:.2f} imgs/s", flush=True)
    summary["batch_sweep_fps"] = sweep
    flush_summary()

    # --- 3. pallas kernel (fwd + grad: the custom-VJP backward is a second
    # pallas program and must also survive real Mosaic lowering) ----------
    if not args.skip_pallas:
        from improved_body_parts_tpu.ops.pallas_focal import parity_benchmark

        S, N, H, C = (2, 2, 32, 50) if args.quick else (4, 4, 128, 50)
        try:
            summary["pallas"] = parity_benchmark(
                stacks=S, batch=N, hw=H, channels=C, iters=iters,
                interpret=platform == "cpu")
            print(f"pallas: {summary['pallas']}", flush=True)
        except Exception as e:  # noqa: BLE001 — Mosaic may reject the kernel
            summary["pallas"] = {"error": f"{type(e).__name__}: {e}"}
            print(f"pallas FAILED under real lowering: {e}", flush=True)
        flush_summary()

    # --- 4. compact end-to-end (planted workload) ------------------------
    if not args.skip_e2e:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from e2e_bench import PlantedModel, planted_maps, synth_images

        from improved_body_parts_tpu.infer import (
            Predictor, decode_compact, pipelined_inference)
        from improved_body_parts_tpu.infer.decode import CompactOverflow

        nprng = np.random.default_rng(0)
        planted = PlantedModel(
            model, planted_maps(cfg.skeleton, 3, nprng,
                                canvas=max(1024, 2 * size)), cfg.skeleton)
        pred = Predictor(planted, variables, cfg.skeleton)
        stream = synth_images(args.e2e_images, size, nprng)

        def one(im):
            try:
                return decode_compact(pred.predict_compact(im), pred.params,
                                      cfg.skeleton)
            except CompactOverflow:
                return []

        e2e = {"planted_people": 3, "images": len(stream)}
        summary["e2e_compact"] = e2e  # flushed after EVERY measurement
        n_people = len(one(stream[0]))  # compile
        t0 = time.perf_counter()
        for im in stream:
            one(im)
        e2e["compact_fps"] = round(len(stream)
                                   / (time.perf_counter() - t0), 2)
        flush_summary()
        print(f"e2e compact: {e2e['compact_fps']} FPS "
              f"({n_people} people/img)", flush=True)

        t0 = time.perf_counter()
        n = sum(1 for _ in pipelined_inference(pred, stream,
                                               decode_workers=4,
                                               compact=True))
        e2e["compact_pipelined_fps"] = round(n / (time.perf_counter() - t0),
                                             2)
        flush_summary()
        print(f"e2e compact pipelined: {e2e['compact_pipelined_fps']} FPS",
              flush=True)

        b = 4 if args.quick else 8
        list(pipelined_inference(pred, stream[:b], decode_workers=4,
                                 compact_batch=b))  # compile
        t0 = time.perf_counter()
        n = sum(1 for _ in pipelined_inference(pred, stream,
                                               decode_workers=4,
                                               compact_batch=b))
        e2e["compact_batch_fps"] = round(n / (time.perf_counter() - t0), 2)
        e2e["compact_batch"] = b
        flush_summary()
        print(f"e2e compact batch({b}): {e2e['compact_batch_fps']} FPS",
              flush=True)

    # --- 5. train step (state-chained by construction) -------------------
    if not args.skip_train:
        from improved_body_parts_tpu.train import (
            create_train_state, make_train_step)

        b = 2 if args.quick else 8
        label_hw = size // cfg.skeleton.stride
        t_imgs = jnp.asarray(
            np.random.default_rng(1).uniform(0, 1, (b, size, size, 3)),
            jnp.float32)
        labels = jnp.asarray(
            np.random.default_rng(2).uniform(
                0, 1, (b, label_hw, label_hw, cfg.skeleton.num_layers)),
            jnp.float32)
        mask = jnp.ones((b, label_hw, label_hw, 1), jnp.float32)
        import optax

        opt = optax.sgd(1e-4, momentum=0.9)
        state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                                   t_imgs[:1])
        step = make_train_step(model, cfg, opt, donate=True)
        state, loss = step(state, t_imgs, mask, labels)
        jax.block_until_ready(loss)
        n_steps = 3 if args.quick else 15
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, loss = step(state, t_imgs, mask, labels)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / n_steps
        summary["train_step"] = {
            "batch": b, "step_ms": round(dt * 1e3, 2),
            "imgs_per_sec": round(b / dt, 2),
            "loss_finite": bool(np.isfinite(float(loss))),
        }
        flush_summary()
        print(f"train step b{b}: {dt * 1e3:.1f} ms "
              f"({b / dt:.1f} imgs/s)", flush=True)

    # --- 6. optional profile trace --------------------------------------
    if args.profile_dir:
        try:
            # compile + warm OUTSIDE the trace so it shows steady-state
            # steps, not a multi-second compile
            jax.block_until_ready(fwd(variables, imgs))
            with jax.profiler.trace(args.profile_dir):
                for _ in range(5):
                    out = fwd(variables, imgs)
                jax.block_until_ready(out)
            summary["profile_dir"] = args.profile_dir
            print(f"trace written to {args.profile_dir}", flush=True)
        except Exception as e:  # noqa: BLE001 — never lose the session
            summary["profile_error"] = f"{type(e).__name__}: {e}"
            print(f"profiling failed (session results kept): {e}",
                  flush=True)

    flush_summary()
    print(strict_dumps(summary), flush=True)


if __name__ == "__main__":
    main()
