#!/usr/bin/env python
"""Closed-loop serving benchmark: throughput and tail latency vs. offered
load, batched serving vs. sequential single-image inference.

K client threads each run a closed loop (submit → wait for decoded
skeletons → submit the next image) against the dynamic batcher
(``serve.DynamicBatcher``).  The verdict arm compares against K clients
driving ``Predictor.predict_compact`` + decode behind a global lock —
the reference's serial evaluate.py protocol exposed as-is to concurrent
callers.  Two strictly stronger hand-rolled baselines are also recorded
for honesty (``sequential_overlapped``: decode outside the lock;
``sequential_concurrent``: no coordination at all), and
``beats_all_sequential_baselines`` reports the comparison against the
best of all three.  The batcher wins by keeping the 2N forward lanes of
the compact batch program occupied (PERF_AUDIT_B.json: the batched
forward runs at ~2× the single-image rate on the chip) and by
overlapping decode with the next batch's forward.

Writes SERVE_BENCH.json: imgs/sec, p50/p95/p99 latency, mean batch
occupancy and the full occupancy histogram per offered load, plus the
batched-vs-sequential verdict at the highest load — and the
device-decode vs host-pool A/B (``decode_ab``): the batcher's default
fused lane (forward + greedy assembly in ONE device program,
``ops.assembly``) against the pre-fusion decode-thread-pool lane,
interleaved rounds, median per-round ratio verdict.

``--proc-only`` instead runs the thread-pool vs process-pool A/B →
PROC_BENCH.json: ``EnginePool`` of in-process worker threads vs
``ProcessRouter`` worker processes on the shared-memory wire, SAME
GIL-shaped predictor both arms (a GIL-held host-work spin + a
GIL-released device wait), interleaved rounds, per-arm compile-delta
recompile accounting, plus a SIGKILL chaos arm proving every submitted
future resolves across a worker kill -9 and the worker respawns.

    python tools/serve_bench.py --clients 1,4,8 --requests 12 \
        --out SERVE_BENCH.json
    python tools/serve_bench.py --proc-only --proc-rounds 5 \
        --requests 20 --out PROC_BENCH.json
"""
import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


def run_clients(n_clients, requests, work_fn):
    """Spawn ``n_clients`` closed-loop clients, each issuing ``requests``
    calls of ``work_fn(client_id, i)``; returns (wall_s, latencies)."""
    latencies = [[] for _ in range(n_clients)]
    errors = []

    def client(cid):
        try:
            for i in range(requests):
                t0 = time.perf_counter()
                work_fn(cid, i)
                latencies[cid].append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — surfaced after join
            errors.append(e)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return wall, [v for lat in latencies for v in lat]


def lat_summary(latencies):
    from improved_body_parts_tpu.utils import PercentileMeter

    m = PercentileMeter(capacity=max(len(latencies), 1))
    for v in latencies:
        m.update(v)
    return {k: round(v, 2) for k, v in m.summary(scale=1e3).items()}


def bench_sequential(pred, decode_one, images, n_clients, requests,
                     mode="serial"):
    """K clients, each a closed loop over ``predict_compact`` + decode —
    today's per-image entry point driven by concurrent callers, in three
    flavours:

    - ``serial``: a global lock around forward + decode (the reference's
      serial evaluate.py protocol exposed as-is);
    - ``overlap``: lock around the forward only, decode concurrent on
      the client threads (a strictly stronger hand-rolled baseline);
    - ``concurrent``: no coordination at all — every client calls
      ``predict_compact`` directly (the literal naive deployment).
    """
    lock = threading.Lock()

    def work(cid, i):
        img = images[(cid + i * n_clients) % len(images)]
        if mode == "concurrent":
            decode_one(pred.predict_compact(img), img)
        elif mode == "overlap":
            with lock:  # one image on the device at a time
                res = pred.predict_compact(img)
            decode_one(res, img)
        else:
            with lock:  # the serial loop: forward + decode per request
                decode_one(pred.predict_compact(img), img)

    # untimed compile pass per distinct shape
    for img in {im.shape: im for im in images}.values():
        decode_one(pred.predict_compact(img), img)
    wall, lats = run_clients(n_clients, requests, work)
    total = n_clients * requests
    return {"clients": n_clients, "requests": total, "mode": mode,
            "imgs_per_sec": round(total / wall, 3),
            "latency_ms": lat_summary(lats)}


def make_server(pred, params, args, use_native, n_clients, devices=None,
                registry=None, device_decode=True):
    from improved_body_parts_tpu.serve import DynamicBatcher

    # auto: one decode lane per client, but never more threads than
    # cores — past that they just thrash the GIL against the dispatcher
    workers = args.decode_workers or max(2, min(n_clients,
                                                os.cpu_count() or 2))
    return DynamicBatcher(pred, params, max_batch=args.max_batch,
                          max_wait_ms=args.max_wait_ms,
                          max_queue=args.max_queue,
                          decode_workers=workers,
                          eager_idle_flush=not args.occupancy_first,
                          use_native=use_native, devices=devices,
                          registry=registry, device_decode=device_decode)


def bench_decode_ab(pred, params, images, sizes, n_clients, requests,
                    args, use_native, devices, rounds):
    """Device-decode lane vs host-pool lane, interleaved A/B rounds.

    The two arms are the SAME batcher configuration differing only in
    ``device_decode`` — fused on-device assembly + inline finish vs the
    decode thread pool.  Rounds alternate device/host slices and the
    verdict is the median per-round ratio (the standing ROADMAP bench
    protocol: slow host drift hits both arms of a round equally, and
    the median ignores the one round a cron job stole).
    """
    out = {"rounds": rounds, "clients": n_clients,
           "requests_per_round": n_clients * requests,
           "note": "On a CPU host both lanes share the same few cores, "
                   "so the fused lane's win is the freed decode-pool "
                   "CPU only; on-chip the assembly rides the idle "
                   "accelerator while the host pool serialized on the "
                   "GIL — the margin is expected to widen there.",
           "device_imgs_per_sec": [], "host_pool_imgs_per_sec": []}
    with make_server(pred, params, args, use_native, n_clients,
                     devices=devices, device_decode=True) as dev_srv, \
            make_server(pred, params, args, use_native, n_clients,
                        devices=devices, device_decode=False) as host_srv:
        dev_srv.warmup(sizes)
        host_srv.warmup(sizes)
        for _ in range(rounds):
            dev = run_serve_slice(dev_srv, images, n_clients, requests)
            host = run_serve_slice(host_srv, images, n_clients, requests)
            out["device_imgs_per_sec"].append(dev["imgs_per_sec"])
            out["host_pool_imgs_per_sec"].append(host["imgs_per_sec"])
            print(f"decode round: device {dev['imgs_per_sec']} vs "
                  f"host-pool {host['imgs_per_sec']} imgs/s", flush=True)
        snap = dev_srv.metrics.snapshot()
        out["device_p95_ms"] = dev["latency_ms"]["p95"]
        out["host_pool_p95_ms"] = host["latency_ms"]["p95"]
    ratios = sorted(d / h for d, h in zip(out["device_imgs_per_sec"],
                                          out["host_pool_imgs_per_sec"]))
    out["per_round_ratio"] = [round(r, 3) for r in ratios]
    out["median_round_ratio"] = round(ratios[len(ratios) // 2], 3)
    out["device_decode_beats_host_pool"] = bool(
        out["median_round_ratio"] > 1.0)
    # the observable fallback rate: every request the fused lane served
    # inline vs demoted to the pool (capacity overflows)
    out["decode_fused"] = snap["decode_fused"]
    out["decode_host_fallback"] = snap["decode_host_fallback"]
    return out


def run_serve_slice(server, images, n_clients, requests):
    """One closed-loop measurement slice against a running batcher.
    Load-shed (``ServerOverloaded``) retries ride the shared policy
    helper — jittered exponential backoff, exactly what a production
    client runs — and are REPORTED, not counted as failures."""
    import threading as _threading

    from improved_body_parts_tpu.serve import submit_with_retry

    retries = [0]
    retries_lock = _threading.Lock()

    def work(cid, i):
        img = images[(cid + i * n_clients) % len(images)]
        fut, n = submit_with_retry(server.submit, img,
                                   base_s=0.002, max_s=0.05)
        if n:
            with retries_lock:
                retries[0] += n
        fut.result()

    wall, lats = run_clients(n_clients, requests, work)
    total = n_clients * requests
    return {"clients": n_clients, "requests": total,
            "imgs_per_sec": round(total / wall, 3),
            "latency_ms": lat_summary(lats),
            "shed_retries": retries[0]}


# --------------------------------------------------------------------- #
# thread-pool vs process-pool A/B (--proc-only → PROC_BENCH.json)        #
# --------------------------------------------------------------------- #
class _GilBoundPredictor:
    """Deterministic serve workload with the REAL serve-path GIL shape:
    a pure-Python accumulation loop that HOLDS the GIL (the host-side
    decode/orchestration milliseconds) followed by a blocking wait that
    RELEASES it (device execution — XLA drops the GIL for the dispatch
    wait), then the constant predictor's bit-deterministic person
    table.  This is what the thread-vs-process A/B must isolate: on a
    multi-core host the process arm buys real parallelism for the
    GIL-held part; on ANY host the thread arm additionally pays the
    GIL convoy — a worker thread waking from its device wait stalls up
    to the 5 ms switch interval behind a sibling's spin before it can
    run, while the OS preempts between processes immediately."""

    def __init__(self, num_parts=18, n_people=4, spin=80000,
                 device_s=0.025):
        from improved_body_parts_tpu.serve.worker import (
            constant_predictor)

        self._inner = constant_predictor(num_parts=num_parts,
                                         n_people=n_people)
        self.spin = int(spin)
        self.device_s = float(device_s)

    def serve_one(self, image):
        acc = int(image[0, 0, 0]) if image.size else 0
        for _ in range(self.spin):        # GIL-held host work
            acc = (acc * 1103515245 + 12345) & 0x7FFFFFFF
        if self.device_s:
            time.sleep(self.device_s)     # GIL-released device wait
        return self._inner.serve_one(image)


def gil_predictor(num_parts=18, n_people=4, spin=80000,
                  device_s=0.025):
    """Worker factory spec target (``serve_bench:gil_predictor``) —
    the process arm's child builds its own instance; the thread arm
    calls it in-process.  Same code, same image-determined output:
    the A/B isolates WHERE ``serve_one`` runs."""
    return _GilBoundPredictor(num_parts=num_parts, n_people=n_people,
                              spin=spin, device_s=device_s)


class ThreadWorkerEngine:
    """The process worker's in-process twin: ONE predictor behind ONE
    worker thread with the same slot-bounded admission
    (``ServerOverloaded`` past ``slots``) behind the same duck-typed
    engine contract — so ``EnginePool([ThreadWorkerEngine...])`` vs
    ``ProcessRouter`` differ in exactly one variable: threads under a
    shared GIL vs processes with their own interpreters."""

    def __init__(self, pred, *, slots=8):
        import queue as queue_mod

        from improved_body_parts_tpu.serve import ServeMetrics

        self.pred = pred
        self.slots = slots
        self.metrics = ServeMetrics()
        self._q = queue_mod.Queue()
        self._sem = threading.BoundedSemaphore(slots)
        self._running = False
        self._draining = False
        self._thread = None

    @property
    def draining(self):
        return self._draining

    def start(self):
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="thread-worker")
        self._thread.start()
        return self

    def _loop(self):
        from improved_body_parts_tpu.serve import DeadlineExceeded
        from improved_body_parts_tpu.serve.metrics import HOPS

        while True:
            item = self._q.get()
            if item is None:
                return
            fut, img, deadline, t0, ctx = item
            try:
                t_pickup = time.perf_counter()
                if deadline is not None and t_pickup > deadline:
                    raise DeadlineExceeded("deadline expired in queue")
                res = self.pred.serve_one(img)
                t_exec1 = time.perf_counter()
                self.metrics.on_decode(fused=True)
                t_fin = time.perf_counter()
                self.metrics.on_complete(t_fin - t0)
                # the same 5-hop partition the process wire stamps, so
                # request_report's chain-coverage check holds over both
                # arms (no batch window and no separate decode step
                # here — those hops are legitimately ~0)
                ctx.finish("ok", hops=list(zip(
                    HOPS, (t_pickup - t0, 0.0, t_exec1 - t_pickup,
                           t_fin - t_exec1, 0.0))))
                fut.set_result(res)
            except BaseException as e:  # noqa: BLE001 — per request
                self.metrics.on_fail(
                    expired=type(e).__name__ == "DeadlineExceeded")
                ctx.finish(f"error:{type(e).__name__}")
                fut.set_exception(e)
            finally:
                self._sem.release()

    def submit(self, image, *, deadline_s=None):
        from concurrent.futures import Future

        from improved_body_parts_tpu.serve import (
            DeadlineExceeded, ServerOverloaded)

        if self._draining:
            self.metrics.on_reject()
            raise ServerOverloaded("thread worker is draining")
        if not self._running:
            raise RuntimeError("ThreadWorkerEngine is not running")
        if deadline_s is not None and deadline_s <= 0:
            self.metrics.on_expire_rejected()
            raise DeadlineExceeded(
                f"deadline_s={deadline_s} already expired at submit")
        if not self._sem.acquire(blocking=False):
            self.metrics.on_reject()
            raise ServerOverloaded(
                f"{self.slots} requests in flight (slots)")
        from improved_body_parts_tpu.obs.reqtrace import (
            NULL_NODE, get_reqtrace)

        rt = get_reqtrace()
        # same causal shape as the process engine's per-request node:
        # the A/B arms must pay the SAME tracing cost
        ctx = rt.begin("thread_worker") if rt.enabled else NULL_NODE
        fut = Future()
        t0 = time.perf_counter()
        self.metrics.on_submit()
        self._q.put((fut, image,
                     None if deadline_s is None else t0 + deadline_s,
                     t0, ctx))
        return fut

    def warmup(self, image_sizes, batch_sizes=None):
        return {"bucket_shapes": [], "batch_sizes": [],
                "newly_compiled": 0}

    def stop(self, drain_timeout_s=None):
        if not self._running and self._thread is None:
            return
        self._running = False
        self._draining = True
        self._q.put(None)       # after any queued work: natural drain
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(10.0 if drain_timeout_s is None
                        else drain_timeout_s)
        self._draining = False

    def health(self):
        return {"running": self._running, "draining": self._draining,
                "dispatcher_alive": bool(self._thread is not None
                                         and self._thread.is_alive()),
                "fetchers_alive": 1, "fetchers_expected": 1,
                "queue_depth": self.metrics.depth,
                "batches_in_flight": self._q.qsize(),
                "stall_age_s": self.metrics.stall_age_s()}


def bench_proc_ab(args, telemetry, rounds):
    """Thread-pool vs process-pool A/B over the SAME predictor:
    ``EnginePool`` of N in-process worker threads vs ``ProcessRouter``
    of N worker processes on the shared-memory wire.  Interleaved
    rounds, median per-round ratio verdict, and per-arm compile-delta
    recompile accounting (the latency-audit protocol) — with the twist
    that the process arm's compiles happen in the CHILDREN, so its
    delta adds every worker's own in-process CompileWatch count read
    from the heartbeat block."""
    import numpy as np

    from improved_body_parts_tpu.serve import EnginePool
    from improved_body_parts_tpu.serve.router import ProcessRouter

    workers = args.proc_workers
    n_clients = 2 * workers
    slots = max(8, 2 * n_clients)
    pred_kw = {"num_parts": 18, "n_people": 4, "spin": args.proc_spin,
               "device_s": args.proc_device_ms / 1e3}
    rng = np.random.default_rng(0)
    images = [rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
              for _ in range(8)]
    sizes = [(64, 64)]

    def compiles():
        return int(telemetry.compile_watch.compiles.value)

    out = {"workers": workers, "clients": n_clients, "rounds": rounds,
           "requests_per_round": n_clients * args.requests,
           "cpu_count": os.cpu_count(),
           "predictor": {"spec": "serve_bench:gil_predictor", **pred_kw},
           "note": "Same predictor both arms: a GIL-held host-work "
                   "spin + a GIL-released device wait (the serve-path "
                   "shape). On a multi-core host the process arm adds "
                   "true parallelism for the GIL-held part; on a "
                   "single-core host the margin that remains is the "
                   "GIL convoy (threads wake from the device wait "
                   "into a 5 ms switch-interval stall behind a "
                   "sibling's spin; the OS preempts between processes "
                   "immediately) minus the wire's IPC cost.",
           "thread_imgs_per_sec": [], "process_imgs_per_sec": []}
    arm_recompiles = {"thread": 0, "process": 0}
    thread_pool = EnginePool(
        [ThreadWorkerEngine(gil_predictor(**pred_kw), slots=slots)
         for _ in range(workers)])
    router = ProcessRouter("serve_bench:gil_predictor",
                           num_workers=workers, spec_kwargs=pred_kw,
                           slots=slots, max_image_hw=(64, 64),
                           num_parts=18, max_people=8,
                           registry=telemetry.registry)
    th = pr = None
    with thread_pool, router:
        thread_pool.warmup(sizes)
        router.warmup(sizes)
        telemetry.mark_warm("proc A/B warmup")
        for _ in range(rounds):
            c0 = compiles()
            th = run_serve_slice(thread_pool, images, n_clients,
                                 args.requests)
            arm_recompiles["thread"] += compiles() - c0
            c0 = compiles()
            pr = run_serve_slice(router, images, n_clients,
                                 args.requests)
            arm_recompiles["process"] += compiles() - c0
            out["thread_imgs_per_sec"].append(th["imgs_per_sec"])
            out["process_imgs_per_sec"].append(pr["imgs_per_sec"])
            print(f"proc round: thread {th['imgs_per_sec']} vs "
                  f"process {pr['imgs_per_sec']} imgs/s", flush=True)
        out["thread_p95_ms"] = th["latency_ms"]["p95"]
        out["process_p95_ms"] = pr["latency_ms"]["p95"]
        out["worker_stats"] = router.worker_stats()
        arm_recompiles["process"] += sum(
            w["recompiles_post_warmup"] for w in out["worker_stats"])
        # hop waterfalls live on each ENGINE's metrics (on_hops), not
        # the pool's routing-level object; reservoir percentiles don't
        # merge exactly across workers, so commit the per-worker
        # waterfalls (matching the registry's {replica=,hop=} labels)
        # and merge only the conservation frac, which sums exactly
        wsnaps = [w.metrics.snapshot() for w in router.workers]
        # worker-VIEW readout over the shm telemetry block (the fleet
        # plane): per-worker hop quantiles measured in the process that
        # paid them, plus the cross-boundary conservation ledger —
        # router-view submitted vs Σ worker-view served + in-flight
        fleet = router.fleet_state()
    out["per_arm_recompiles_post_warmup"] = arm_recompiles
    out["process_worker_hop_quantiles_ms"] = [
        {"worker": w["worker"],
         "published": w["telemetry"].get("published", False),
         "hops_ms": {
             hop: {"count": int(h["count"]),
                   "p50": round(h["p50_s"] * 1e3, 3),
                   "p95": round(h["p95_s"] * 1e3, 3),
                   "p99": round(h["p99_s"] * 1e3, 3)}
             for hop, h in (w["telemetry"].get("hops") or {}).items()}}
        for w in fleet["workers"]]
    out["cross_boundary_conservation"] = fleet["conservation"]
    # the thread arm has no hop decomposition (no wire stamps), so
    # only the process arm gets the waterfall + conservation readout
    out["process_hops_ms_per_worker"] = [s["hops_ms"] for s in wsnaps]
    hop_sum = sum(h["sum"] for s in wsnaps for h in s["hops_ms"].values())
    e2e_sum = sum(s["latency_ms"]["mean"] * s["latency_ms"]["count"]
                  for s in wsnaps)
    out["process_hop_conservation_frac"] = (
        round(hop_sum / e2e_sum, 4) if e2e_sum > 0 else None)
    ratios = sorted(p / t for p, t in zip(out["process_imgs_per_sec"],
                                          out["thread_imgs_per_sec"]))
    out["per_round_ratio"] = [round(r, 3) for r in ratios]
    out["median_round_ratio"] = round(ratios[len(ratios) // 2], 3)
    out["multi_core_host"] = bool((os.cpu_count() or 1) > 1)
    out["process_beats_thread"] = bool(out["median_round_ratio"] >= 1.0)
    # the gate: on a multi-core host the process arm must win outright
    # (that is the point of process isolation — N workers, N cores).  A
    # single-core host cannot grant parallelism to EITHER arm, so the
    # measurable claim degrades to parity: the shm wire + process
    # isolation cost stays inside tolerance, and the QPS win waits for
    # cores (the SIGKILL-survival win is unconditional either way).
    # The tolerance is a transport-regression TRIPWIRE, not a
    # parallelism claim: the per-request isolation tax (two scheduler
    # wake hops + encode/decode + two slot-row copies) measures
    # 5-15% of a 45 ms request cycle and run-to-run medians drift
    # ±0.06 on a shared single-core host, while the transport
    # pathology this check exists to catch (an mp.Queue feeder thread
    # on each hop, caught during development and replaced with raw
    # one-way pipes) costs 25-30%.
    out["parity_tolerance"] = 0.85
    out["single_core_parity"] = bool(
        out["median_round_ratio"] >= out["parity_tolerance"])
    out["verdict_ok"] = bool(
        out["process_beats_thread"] if out["multi_core_host"]
        else out["single_core_parity"])
    return out


def bench_proc_chaos(args):
    """SIGKILL across the process boundary mid-batch: every submitted
    future must RESOLVE (a result after pool failover, or a typed
    error — never a hang), the killed worker must come back through
    the supervisor lifecycle (>= 1 real respawn, fresh pid), and the
    fleet keeps answering afterwards."""
    import signal

    import numpy as np

    from improved_body_parts_tpu.serve.router import ProcessRouter

    workers = max(2, args.proc_workers)
    n_inflight = 6
    img = np.full((48, 48, 3), 7, dtype=np.uint8)
    with ProcessRouter(
            "improved_body_parts_tpu.serve.worker:constant_predictor",
            num_workers=workers,
            spec_kwargs={"num_parts": 18, "n_people": 2,
                         "delay_s": 0.25},
            slots=16, max_image_hw=(64, 64), num_parts=18,
            max_people=8, restart_after_s=0.3,
            probe_interval_s=0.05) as router:
        router.submit(img).result(timeout=60)       # path probe
        pid0 = router.workers[0].worker_stats()["pid"]
        futs = [router.submit(img) for _ in range(n_inflight)]
        time.sleep(0.05)                            # mid-batch
        os.kill(pid0, signal.SIGKILL)
        outcomes = {"ok": 0, "error": 0}
        for f in futs:
            try:
                f.result(timeout=60)
                outcomes["ok"] += 1
            except Exception:  # noqa: BLE001 — typed resolve counts
                outcomes["error"] += 1
        deadline = time.perf_counter() + 30
        while (router.workers[0].restarts < 2
               and time.perf_counter() < deadline):
            time.sleep(0.05)
        router.submit(img).result(timeout=60)       # fleet answers
        stats = router.worker_stats()
        counters = router.counters()
    resolved = outcomes["ok"] + outcomes["error"]
    return {"injection": "SIGKILL worker 0 mid-batch",
            "in_flight_at_kill": n_inflight,
            "resolved": resolved,
            "resolved_ok": outcomes["ok"],
            "resolved_error": outcomes["error"],
            "all_futures_resolved": bool(resolved == n_inflight),
            "killed_pid": pid0,
            "respawned_pid": stats[0]["pid"],
            "respawned": bool(stats[0]["pid"] not in (None, pid0)
                              and stats[0]["restarts"] >= 2),
            "worker_respawns": counters["worker_respawns"],
            "fenced": counters["fenced"],
            "failovers": counters["failovers"],
            "pool_restarts": counters["restarts"],
            "post_respawn_answered": True}


def bench_serve(pred, params, images, sizes, n_clients, requests, args,
                use_native, devices=None):
    with make_server(pred, params, args, use_native, n_clients,
                     devices) as server:
        warm = server.warmup(sizes)
        out = run_serve_slice(server, images, n_clients, requests)
        snap = server.metrics.snapshot()
    out.update({
        "mean_batch_occupancy": snap["mean_batch_occupancy"],
        "occupancy_histogram": snap["occupancy_histogram"],
        "queue_depth_peak": snap["queue_depth_peak"],
        # the per-hop decomposition (queue/batch_formation/device/
        # decode/deliver) alongside the e2e numbers, plus the
        # conservation readout (hop sums / e2e sums — exact partition
        # by construction, see serve.metrics.HOPS)
        "hops_ms": snap["hops_ms"],
        "hop_conservation_frac": snap["hop_conservation_frac"],
        "warmup": {"bucket_shapes": [list(s) for s
                                     in warm["bucket_shapes"]],
                   "batch_sizes": list(warm["batch_sizes"]),
                   "newly_compiled": warm["newly_compiled"]}})
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--sizes", default="512",
                    help="comma-separated square image sizes (mixed sizes "
                         "exercise multi-bucket coalescing)")
    ap.add_argument("--requests", type=int, default=12,
                    help="closed-loop requests per client")
    ap.add_argument("--clients", default="1,4,8",
                    help="offered-load sweep for the batched arm")
    ap.add_argument("--baseline-clients", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=3,
                    help="alternating sequential/serve verdict rounds — "
                         "interleaving makes the comparison robust to "
                         "host load drift between arms")
    ap.add_argument("--decode-rounds", type=int, default=0,
                    help="device-decode vs host-pool A/B rounds "
                         "(0 = same as --rounds)")
    ap.add_argument("--no-decode-ab", action="store_true",
                    help="skip the device-decode vs host-pool A/B "
                         "(bench.py's full-serve key passes this: the "
                         "A/B has its own budget-gated 'decode' key)")
    ap.add_argument("--decode-only", action="store_true",
                    help="run ONLY the device-decode vs host-pool A/B "
                         "(bench.py's budget-bounded 'decode' key); "
                         "skips the sequential baselines, the load "
                         "sweep and the batched-vs-sequential verdict")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-wait-ms", type=float, default=100.0,
                    help="coalescing deadline (the idle-device flush "
                         "makes throughput insensitive to it; it bounds "
                         "added latency under load)")
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--occupancy-first", action="store_true",
                    help="disable the eager idle-device flush: only "
                         "max_batch/deadline flushes, maximizing lane "
                         "occupancy (the right trade where full lanes "
                         "run disproportionately faster)")
    ap.add_argument("--decode-workers", type=int, default=0,
                    help="0 = auto (match the client count, like the "
                         "sequential baseline's concurrent decodes)")
    ap.add_argument("--boxsize", type=int, default=0,
                    help="override InferenceModelParams.boxsize (0 = "
                         "default protocol); set to the image size to "
                         "keep CPU smoke runs small")
    ap.add_argument("--planted", type=int, default=2,
                    help="plant GT-style maps for N synthetic people "
                         "(realistic decode workload, as tools/e2e_bench)")
    ap.add_argument("--params-dtype", default="auto",
                    choices=["auto", "bf16", "fp32"])
    ap.add_argument("--no-native", action="store_true")
    ap.add_argument("--devices", type=int, default=0,
                    help="device replicas the batcher serves across "
                         "(data-parallel serving). 0 = all visible "
                         "devices; on a CPU host, N > 1 creates N "
                         "virtual host devices")
    ap.add_argument("--proc-only", action="store_true",
                    help="run ONLY the thread-pool vs process-pool A/B "
                         "+ the SIGKILL chaos arm (bench.py's "
                         "budget-bounded 'procpool' key; the committed "
                         "PROC_BENCH.json); skips the model build — "
                         "both arms serve the GIL-shaped predictor")
    ap.add_argument("--proc-workers", type=int, default=2,
                    help="worker count per arm of the proc A/B")
    ap.add_argument("--proc-rounds", type=int, default=0,
                    help="interleaved thread/process verdict rounds "
                         "(0 = same as --rounds)")
    ap.add_argument("--proc-spin", type=int, default=80000,
                    help="GIL-held host-work iterations per request in "
                         "the proc A/B predictor (~5 ms at default)")
    ap.add_argument("--proc-device-ms", type=float, default=40.0,
                    help="GIL-released device-wait per request in the "
                         "proc A/B predictor (default matches a "
                         "batch-inference-class device step so the "
                         "fixed per-request isolation tax is "
                         "amortized the way production traffic "
                         "amortizes it)")
    ap.add_argument("--telemetry-sink", default="auto",
                    help="JSONL event stream for the run ('auto' = "
                         "<out>_events.jsonl next to --out, 'none' "
                         "disables); the path lands in the output JSON "
                         "as telemetry_events")
    ap.add_argument("--telemetry-port", type=int, default=-1,
                    help="serve /metrics + /snapshot live during the "
                         "bench (0 = ephemeral port, -1 off)")
    ap.add_argument("--out", default="SERVE_BENCH.json")
    args = ap.parse_args()

    if args.devices > 1:
        # must land before the first jax import; only affects the host
        # (CPU) platform — accelerators expose their real chips
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count"
                     f"={args.devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import numpy as np

    all_devices = devices_with_timeout(900)
    platform = all_devices[0].platform
    serve_devices = (all_devices[:args.devices] if args.devices > 0
                     else all_devices)
    print(f"platform={platform} serve_devices={len(serve_devices)}",
          flush=True)

    from improved_body_parts_tpu.obs import Registry, RunTelemetry

    sink_path = None
    if args.telemetry_sink not in ("none", ""):
        sink_path = (os.path.splitext(args.out)[0] + "_events.jsonl"
                     if args.telemetry_sink == "auto"
                     else args.telemetry_sink)
    telemetry = RunTelemetry(
        sink_path, registry=Registry(),
        http_port=(args.telemetry_port if args.telemetry_port >= 0
                   else None),
        run_meta={"tool": "serve_bench", "config": args.config,
                  "platform": platform})
    if telemetry.server is not None:
        print(f"telemetry: {telemetry.server.url}/metrics", flush=True)

    # --- thread-pool vs process-pool A/B (no model: both arms serve the
    # GIL-shaped predictor; worker processes ride the shm wire) --------
    if args.proc_only:
        report = {"platform": platform, "config": args.config,
                  "telemetry_events": sink_path,
                  "requests_per_client": args.requests,
                  "note": "thread-pool vs process-pool A/B on the "
                          "shared-memory wire + SIGKILL chaos arm; "
                          "interleaved rounds, median per-round ratio "
                          "verdict, per-arm compile-delta recompile "
                          "accounting (workers count their own "
                          "compiles in-process)."}

        def flush():
            with open(args.out, "w") as f:
                strict_dump(report, f, indent=2)

        rounds = args.proc_rounds or max(1, args.rounds)
        report["proc_ab"] = bench_proc_ab(args, telemetry, rounds)
        flush()
        telemetry.emit(
            "proc_ab",
            median_round_ratio=report["proc_ab"]["median_round_ratio"],
            process_beats_thread=report["proc_ab"][
                "process_beats_thread"])
        print(f"proc A/B: median ratio "
              f"{report['proc_ab']['median_round_ratio']} "
              f"(multi_core_host="
              f"{report['proc_ab']['multi_core_host']}, verdict_ok="
              f"{report['proc_ab']['verdict_ok']})", flush=True)
        report["proc_chaos"] = bench_proc_chaos(args)
        report["recompiles_post_warmup"] = sum(
            report["proc_ab"]["per_arm_recompiles_post_warmup"].values())
        telemetry.emit("proc_chaos", **{
            k: report["proc_chaos"][k]
            for k in ("all_futures_resolved", "resolved",
                      "worker_respawns", "failovers")})
        telemetry.close()
        flush()
        print(strict_dumps({
            "verdict_ok": report["proc_ab"]["verdict_ok"],
            "multi_core_host": report["proc_ab"]["multi_core_host"],
            "median_round_ratio":
                report["proc_ab"]["median_round_ratio"],
            "chaos_all_futures_resolved":
                report["proc_chaos"]["all_futures_resolved"],
            "chaos_respawned": report["proc_chaos"]["respawned"]}))
        return

    from e2e_bench import PlantedModel, planted_maps, synth_images

    from improved_body_parts_tpu.config import (
        InferenceModelParams, get_config)
    from improved_body_parts_tpu.infer.pipeline import compact_decode_fn
    from improved_body_parts_tpu.infer.predict import Predictor
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.utils.precision import resolve_params_dtype

    cfg = get_config(args.config)
    model = build_model(cfg)
    rng = np.random.default_rng(0)
    sizes = [int(s) for s in args.sizes.split(",")]

    import jax.numpy as jnp

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, sizes[0], sizes[0], 3)),
                           train=False)
    variables = resolve_params_dtype(args.params_dtype, variables)
    if args.planted > 0:
        # canvas sized so the planted people land INSIDE the valid
        # (visible) region of the benched image sizes — with the default
        # 1024 canvas a 256px bench sees almost nobody and the decode
        # stage is benched on near-empty maps
        canvas = max(int(max(sizes) / 0.6) + 64, 640)
        model = PlantedModel(model, planted_maps(cfg.skeleton, args.planted,
                                                 rng, canvas=canvas),
                             cfg.skeleton)
    model_params = (InferenceModelParams(boxsize=args.boxsize)
                    if args.boxsize else None)
    pred = Predictor(model, variables, cfg.skeleton,
                     model_params=model_params)
    params = pred.params
    use_native = not args.no_native

    # a handful of distinct images per size, cycled by the clients
    images = [im for s in sizes for im in synth_images(4, s, rng)]
    size_list = [(s, s) for s in sizes]

    report = {"platform": platform, "config": args.config, "sizes": sizes,
              "telemetry_events": sink_path,
              "serve_devices": len(serve_devices),
              "occupancy_first": bool(args.occupancy_first),
              "note": "closed-loop clients; verdict rounds interleave the "
                      "arms so host drift hits both equally. On the CPU "
                      "backend batch lanes only pay at 512px-class inputs; "
                      "on-chip, full lanes run at ~2x the single-image "
                      "rate (PERF_AUDIT_B.json), where max_batch=8 and "
                      "the default eager idle-flush are the right knobs.",
              "planted_people": args.planted,
              "requests_per_client": args.requests,
              "max_batch": args.max_batch, "max_wait_ms": args.max_wait_ms,
              "max_queue": args.max_queue,
              "decode_workers": args.decode_workers,
              "bucket_shapes": [list(s) for s in
                                pred.enumerate_bucket_shapes(size_list)]}

    def flush():
        with open(args.out, "w") as f:
            strict_dump(report, f, indent=2)

    decode_one = compact_decode_fn(pred, params, use_native=use_native)

    # --- device-decode vs host-pool A/B (interleaved rounds) ----------
    n_peak = max(int(c) for c in args.clients.split(","))
    if args.decode_only and args.no_decode_ab:
        ap.error("--decode-only and --no-decode-ab are contradictory")
    if not args.no_decode_ab:
        decode_rounds = args.decode_rounds or max(1, args.rounds)
        report["decode_ab"] = bench_decode_ab(
            pred, params, images, size_list, n_peak, args.requests, args,
            use_native, serve_devices, decode_rounds)
        flush()
        telemetry.emit("decode_ab", **{
            k: report["decode_ab"][k]
            for k in ("median_round_ratio",
                      "device_decode_beats_host_pool",
                      "decode_fused", "decode_host_fallback")})
        print(f"decode A/B: median ratio "
              f"{report['decode_ab']['median_round_ratio']} "
              f"(fused {report['decode_ab']['decode_fused']}, fallback "
              f"{report['decode_ab']['decode_host_fallback']})",
              flush=True)
    if args.decode_only:
        telemetry.close()
        flush()
        print(strict_dumps({"device_decode_beats_host_pool":
                            report["decode_ab"][
                                "device_decode_beats_host_pool"],
                            "median_round_ratio":
                            report["decode_ab"]["median_round_ratio"]}))
        return

    # --- offered-load sweep (context curve) ---------------------------
    for mode, key in (("overlap", "sequential_overlapped"),
                      ("concurrent", "sequential_concurrent")):
        arm = bench_sequential(pred, decode_one, images,
                               args.baseline_clients, args.requests,
                               mode=mode)
        report[key] = arm
        flush()
        print(f"sequential/{mode} x{arm['clients']}: "
              f"{arm['imgs_per_sec']} imgs/s "
              f"p95={arm['latency_ms']['p95']}ms", flush=True)

    report["serve"] = []
    for n in [int(c) for c in args.clients.split(",")]:
        arm = bench_serve(pred, params, images, size_list, n,
                          args.requests, args, use_native,
                          devices=serve_devices)
        report["serve"].append(arm)
        flush()
        telemetry.emit("serve_arm", clients=n,
                       imgs_per_sec=arm["imgs_per_sec"],
                       p95_ms=arm["latency_ms"]["p95"],
                       mean_batch_occupancy=arm["mean_batch_occupancy"])
        print(f"serve x{n}: {arm['imgs_per_sec']} imgs/s "
              f"p95={arm['latency_ms']['p95']}ms "
              f"occupancy={arm['mean_batch_occupancy']}", flush=True)

    # --- verdict: interleaved rounds, batched vs sequential -----------
    # alternating A/B/A/B slices and per-arm TOTALS: slow host drift
    # (shared cores, other tenants) hits both arms equally instead of
    # whichever arm happened to run in the bad minute
    seq_rounds, serve_rounds = [], []
    # the verdict server registers into the run registry: its counters/
    # latency reservoir surface on /metrics (when --telemetry-port is
    # set) alongside the recompile watch — one exposition path
    with make_server(pred, params, args, use_native, n_peak,
                     devices=serve_devices,
                     registry=telemetry.registry) as server:
        server.warmup(size_list)
        # every bucket x batch-size program is compiled: any compile
        # from here on is the silent recompile stall the watch exists for
        telemetry.mark_warm("serve warmup precompile")
        for _ in range(max(1, args.rounds)):
            seq_rounds.append(bench_sequential(
                pred, decode_one, images, args.baseline_clients,
                args.requests))
            serve_rounds.append(run_serve_slice(
                server, images, n_peak, args.requests))
            print(f"round: sequential {seq_rounds[-1]['imgs_per_sec']} vs "
                  f"serve {serve_rounds[-1]['imgs_per_sec']} imgs/s",
                  flush=True)
        verdict_snap = server.metrics.snapshot()

    def total_fps(rounds):
        n = sum(r["requests"] for r in rounds)
        return round(n / sum(r["requests"] / r["imgs_per_sec"]
                             for r in rounds), 3)

    seq_fps, serve_fps = total_fps(seq_rounds), total_fps(serve_rounds)
    report["sequential"] = {**seq_rounds[0],
                            "imgs_per_sec": seq_fps,
                            "per_round_imgs_per_sec":
                            [r["imgs_per_sec"] for r in seq_rounds]}
    report["serve_at_peak_load"] = {
        **serve_rounds[-1], "imgs_per_sec": serve_fps,
        "per_round_imgs_per_sec":
        [r["imgs_per_sec"] for r in serve_rounds],
        # policy-layer retry accounting: sheds the clients absorbed
        # with jittered backoff instead of reporting them as failures
        "shed_retries_total": sum(r["shed_retries"]
                                  for r in serve_rounds),
        "mean_batch_occupancy": verdict_snap["mean_batch_occupancy"],
        "occupancy_histogram": verdict_snap["occupancy_histogram"],
        "queue_depth_peak": verdict_snap["queue_depth_peak"],
        # per-hop p50/p95/p99 over the interleaved verdict rounds
        "hops_ms": verdict_snap["hops_ms"],
        "hop_conservation_frac":
            verdict_snap["hop_conservation_frac"]}
    report["batched_beats_sequential"] = bool(serve_fps > seq_fps)
    report["speedup_at_peak_load"] = round(serve_fps / seq_fps, 3)
    strongest = max(seq_fps,
                    report["sequential_overlapped"]["imgs_per_sec"],
                    report["sequential_concurrent"]["imgs_per_sec"])
    report["beats_all_sequential_baselines"] = bool(serve_fps > strongest)
    # post-warmup compiles during the verdict rounds would mean the
    # precompile missed a shape the traffic actually hit
    report["recompiles_post_warmup"] = int(
        telemetry.compile_watch.recompiles.value)
    telemetry.emit("serve_verdict", sequential_imgs_per_sec=seq_fps,
                   serve_imgs_per_sec=serve_fps,
                   batched_beats_sequential=report[
                       "batched_beats_sequential"])
    telemetry.close()
    flush()
    print(strict_dumps({"batched_beats_sequential":
                        report["batched_beats_sequential"],
                        "speedup": report["speedup_at_peak_load"]}))


if __name__ == "__main__":
    main()
