#!/usr/bin/env python
"""Aggregate the seed-replicated A/B artifacts into mean±spread claims.

Round 4's headline A/B deltas (SWA vs base, device-GT vs host-GT, crowd
masked vs ablated) were single-run; this tool collects the per-seed
artifacts written by the round-5 replication runs
(SYNTH_AP_DEEP_S*.json etc., all evaluated on the same fixed 64-image
big val, seed 777) and reports each delta against the across-seed
spread: a delta smaller than the spread of its own arms is labeled
"neutral", not a win — the honest-labeling rule the round-4 verdict
asked for.

    python tools/ab_summary.py --out AB_SUMMARY.json
"""
import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


def _stats(vals):
    n = len(vals)
    mean = sum(vals) / n
    spread = max(vals) - min(vals)
    sd = (sum((v - mean) ** 2 for v in vals) / (n - 1)) ** 0.5 if n > 1 \
        else 0.0
    return {"n": n, "mean": round(mean, 4), "min": round(min(vals), 4),
            "max": round(max(vals), 4), "range": round(spread, 4),
            "sd": round(sd, 4), "values": [round(v, 4) for v in vals]}


def _collect(pattern, key="ap_trained"):
    out = {}
    for path in sorted(glob.glob(pattern)):
        seed_m = re.search(r"_S(\d+)\.json$", path)
        seed = int(seed_m.group(1)) if seed_m else 0
        with open(path) as f:
            out[seed] = (float(json.load(f)[key]), os.path.basename(path))
    return out


def _pair(arm_a, arm_b, label_a, label_b):
    """Compare two arms over their COMMON seeds."""
    seeds = sorted(set(arm_a) & set(arm_b))
    if not seeds:
        return {"note": f"no common seeds yet ({label_a}: {sorted(arm_a)}, "
                        f"{label_b}: {sorted(arm_b)})"}
    a = [arm_a[s][0] for s in seeds]
    b = [arm_b[s][0] for s in seeds]
    delta = sum(x - y for x, y in zip(a, b)) / len(seeds)
    per_seed = [round(x - y, 4) for x, y in zip(a, b)]
    spread = max(_stats(a)["range"], _stats(b)["range"], 1e-9)
    consistent = all(d > 0 for d in per_seed) or all(d < 0 for d in per_seed)
    if len(seeds) < 2:
        # one seed = the single-run claim this tool exists to retire
        verdict = "insufficient seeds (n=1; no spread evidence)"
    elif abs(delta) <= spread and not consistent:
        verdict = "neutral (|delta| <= across-seed spread)"
    else:
        verdict = (f"{label_a} wins" if delta > 0 else f"{label_b} wins")
    return {"seeds": seeds, label_a: _stats(a), label_b: _stats(b),
            "mean_delta": round(delta, 4), "per_seed_delta": per_seed,
            "across_seed_spread": round(spread, 4),
            "delta_sign_consistent": consistent, "verdict": verdict,
            "sources": sorted({arm_a[s][1] for s in seeds}
                              | {arm_b[s][1] for s in seeds})}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".")
    ap.add_argument("--out", default="AB_SUMMARY.json")
    args = ap.parse_args()
    d = args.dir

    def g(p, key="ap_trained"):
        return _collect(os.path.join(d, p), key)

    base = g("SYNTH_AP_DEEP_S[0-9]*.json")
    swa = g("SYNTH_AP_DEEP_SWA_S[0-9]*.json", key="ap_swa")
    devgt = g("SYNTH_AP_DEEP_DEVICEGT_S[0-9]*.json")
    crowd = g("SYNTH_AP_CROWD_S[0-9]*.json")
    uncrowd = g("SYNTH_AP_CROWD_UNMASKED_S[0-9]*.json")

    summary = {
        "protocol": "per-seed pairs share corpus seed, init seed and the "
                    "fixed 64-image big val (seed 777); synth_deep arms: "
                    "96 images / 10 epochs (SWA: +5 cyclic-LR frozen-BN "
                    "epochs from the base checkpoint); crowd arms: toy "
                    "synth config, 48 images / 60 epochs",
        "swa_vs_base": _pair(swa, base, "swa", "base"),
        "devgt_vs_hostgt": _pair(devgt, base, "device_gt", "host_gt"),
        "crowd_masked_vs_ablated": _pair(crowd, uncrowd, "masked",
                                         "mask_ablated"),
    }
    with open(args.out, "w") as f:
        strict_dump(summary, f, indent=2)
    print(strict_dumps(summary, indent=2))


if __name__ == "__main__":
    main()
