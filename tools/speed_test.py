#!/usr/bin/env python
"""Pure-network inference FPS benchmark
(reference: test_inference_speed.py:90-120; baseline ~38.5 imgs/s at 512x512
on a 2080 Ti, README.md:67).

    python tools/speed_test.py --batch 1 --size 512 --iters 50
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description="network FPS benchmark")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--size", type=int, default=512)
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--fp32", action="store_true", help="disable bf16 compute")
    ap.add_argument("--params-dtype", default="auto",
                    choices=["auto", "bf16", "fp32"],
                    help="weight storage (halves weight HBM traffic per "
                         "pass in bf16); auto = bf16 on TPU, fp32 elsewhere")
    ap.add_argument("--bf16-params", action="store_true",
                    help="deprecated alias for --params-dtype bf16")
    args = ap.parse_args()
    if args.bf16_params and args.params_dtype not in ("auto", "bf16"):
        ap.error("--bf16-params (deprecated) conflicts with "
                 f"--params-dtype {args.params_dtype}; drop the alias")

    import jax
    import jax.numpy as jnp

    from improved_body_parts_tpu.utils import apply_platform_env
    apply_platform_env()  # honour JAX_PLATFORMS even under a sitecustomize

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model

    cfg = get_config(args.config)
    model = build_model(cfg, dtype=jnp.float32 if args.fp32 else None)
    imgs = jnp.zeros((args.batch, args.size, args.size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), imgs, train=False)
    from improved_body_parts_tpu.utils.precision import resolve_params_dtype

    if args.bf16_params:
        params_dtype = "bf16"
    elif args.params_dtype == "auto" and args.fp32:
        # --fp32 is the full-precision baseline: don't let auto sneak
        # bf16 weights under fp32 compute (explicit --params-dtype wins)
        params_dtype = "fp32"
    else:
        params_dtype = args.params_dtype
    variables = resolve_params_dtype(params_dtype, variables)

    @jax.jit
    def forward(variables, imgs):
        return model.apply(variables, imgs, train=False)[-1][0]

    out = forward(variables, imgs)
    jax.block_until_ready(out)
    for _ in range(5):
        out = forward(variables, imgs)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = forward(variables, imgs)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    fps = args.iters * args.batch / dt
    print(f"{fps:.2f} imgs/s  ({dt / args.iters * 1000:.2f} ms/iter, "
          f"batch {args.batch}, {args.size}x{args.size}, "
          f"{'fp32' if args.fp32 else 'bf16'})")


if __name__ == "__main__":
    main()
