#!/usr/bin/env python
"""Training-step throughput on the active platform (chip or CPU).

Times the REAL jitted train step — forward, focal L2, backward, SGD update,
BN batch stats — at full 512x512 resolution across a batch sweep. Unlike an
inference dispatch loop, successive train steps chain through the carried
``TrainState``, so a pooled relay cannot fan them out: the timing is honest
by construction (see tools/perf_audit.py for why that matters here).

The reference trains at batch 4/GPU and claims >90% GPU utilization
(reference: config/config.py:10, README.md:34). It publishes no imgs/s for
training; this records ours, with XLA cost analysis per step.

    python tools/train_bench.py --batches 2 4 8 --out TRAIN_BENCH.json
"""
import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--size", type=int, default=512)
    ap.add_argument("--batches", type=int, nargs="+", default=[2, 4, 8])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--device-gt", action="store_true",
                    help="time the on-device GT-synthesis step variant")
    ap.add_argument("--out", default="TRAIN_BENCH.json")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    devices = devices_with_timeout(900)
    platform = devices[0].platform
    print(f"platform={platform}", flush=True)

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.train import (
        create_train_state, make_optimizer, make_train_step,
        step_decay_schedule)

    cfg = get_config(args.config)
    model = build_model(cfg)
    stride = cfg.skeleton.stride
    label_hw = args.size // stride
    rng = np.random.default_rng(0)

    report = {"platform": platform, "config": args.config, "size": args.size,
              "steps": args.steps, "repeats": args.repeats, "batches": {}}

    def flush():
        with open(args.out, "w") as f:
            strict_dump(report, f, indent=2)

    opt = make_optimizer(cfg, step_decay_schedule(cfg.train,
                                                  steps_per_epoch=100))
    for b in args.batches:
        imgs = jnp.asarray(
            rng.uniform(0, 1, (b, args.size, args.size, 3)), jnp.float32)
        labels = jnp.asarray(
            rng.uniform(0, 1, (b, label_hw, label_hw,
                               cfg.skeleton.num_layers)), jnp.float32)
        mask = jnp.ones((b, label_hw, label_hw, 1), jnp.float32)

        state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                                   imgs[:1])
        step = make_train_step(model, cfg, opt, donate=True)
        compiled = step.lower(state, imgs, mask, labels).compile()
        ca = compiled.cost_analysis() or {}
        gflops = float(ca.get("flops", 0.0)) / 1e9
        gbytes = float(ca.get("bytes accessed", 0.0)) / 1e9

        state, loss = compiled(state, imgs, mask, labels)
        jax.block_until_ready(loss)
        assert np.isfinite(float(loss)), f"non-finite loss at batch {b}"

        reps = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                state, loss = compiled(state, imgs, mask, labels)
            jax.block_until_ready(loss)
            reps.append((time.perf_counter() - t0) / args.steps)
        med = statistics.median(reps)
        entry = {
            "step_ms_median": round(med * 1e3, 3),
            "imgs_per_sec": round(b / med, 2),
            "repeat_spread_ms": [round(r * 1e3, 3) for r in sorted(reps)],
            "hlo_gflops_per_step": round(gflops, 1),
            "hlo_gbytes_per_step": round(gbytes, 3),
            "implied_tflops": round(gflops / 1e3 / med, 1) if gflops else None,
            "implied_hbm_gbps": round(gbytes / med, 1) if gbytes else None,
        }
        report["batches"][b] = entry
        flush()
        print(f"batch {b}: {b / med:7.2f} imgs/s  ({med * 1e3:.1f} ms/step, "
              f"{gflops:.0f} GFLOP -> {entry['implied_tflops']} TFLOP/s, "
              f"{entry['implied_hbm_gbps']} GB/s)", flush=True)

    grid_h, grid_w = cfg.skeleton.grid_shape
    if args.device_gt and (label_hw, label_hw) != (grid_h, grid_w):
        # the on-device synthesizer bakes in the config's grid_shape; a
        # mismatched --size would trace-error (or mis-size the loss)
        print(f"skipping --device-gt: size {args.size} gives a "
              f"{label_hw}x{label_hw} grid but config '{args.config}' "
              f"synthesizes at {grid_h}x{grid_w}", flush=True)
        report["device_gt"] = {"skipped": f"size {args.size} != config grid"}
        flush()
        args.device_gt = False

    if args.device_gt:
        b = args.batches[-1]
        max_people, max_joints = 8, cfg.skeleton.num_parts
        imgs = jnp.asarray(
            rng.uniform(0, 1, (b, args.size, args.size, 3)), jnp.float32)
        joints = np.asarray(
            rng.uniform(0, args.size, (b, max_people, max_joints, 3)),
            np.float32)
        joints[..., 2] = rng.integers(0, 2, joints.shape[:-1])  # visible
        joints = jnp.asarray(joints)
        mask = jnp.ones((b, label_hw, label_hw, 1), jnp.float32)
        mask_all = jnp.ones((b, label_hw, label_hw, 1), jnp.float32)
        state = create_train_state(model, cfg, opt, jax.random.PRNGKey(0),
                                   imgs[:1])
        step = make_train_step(model, cfg, opt, donate=True, device_gt=True)
        state, loss = step(state, imgs, mask, joints, mask_all)
        jax.block_until_ready(loss)
        reps = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                state, loss = step(state, imgs, mask, joints, mask_all)
            jax.block_until_ready(loss)
            reps.append((time.perf_counter() - t0) / args.steps)
        dt = statistics.median(reps)
        report["device_gt"] = {
            "batch": b, "step_ms_median": round(dt * 1e3, 3),
            "imgs_per_sec": round(b / dt, 2),
            "repeat_spread_ms": [round(r * 1e3, 3) for r in sorted(reps)]}
        flush()
        print(f"device-gt batch {b}: {b / dt:.2f} imgs/s", flush=True)

    print(strict_dumps(report))


if __name__ == "__main__":
    main()
