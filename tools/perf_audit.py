#!/usr/bin/env python
"""Audited inference timing: every FPS claim cross-checked against the
compiled executable's own cost analysis.

The quick benchmarks (bench.py, tools/speed_test.py) time a Python dispatch
loop; on a relay-attached chip that can under- or over-state the device rate
(dispatch pipelining, host contention, power ramp). This tool is the careful
version used to *audit* those numbers:

- XLA's ``compiled.cost_analysis()`` FLOP/byte counts for each batch size —
  the implied TFLOP/s and GB/s are printed next to each timing so a
  physically impossible number (above peak) is flagged instead of recorded;
- random (not constant-foldable, not all-zero) inputs, output checksum
  asserted finite;
- R independent repeats of N iterations; median and best repeats reported;
- a chained-latency variant (iteration i+1 consumes a scalar derived from
  iteration i) that defeats dispatch pipelining and measures true
  end-to-end step latency.

Reference headline being audited: 38.5 imgs/s single-image 512x512 on a
2080 Ti (reference: test_inference_speed.py:90-120, README.md:67).

    python tools/perf_audit.py --batches 1 2 4 8 --out PERF_AUDIT.json
"""
import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

# v5e public peak: ~197 TFLOP/s bf16, ~819 GB/s HBM. Used only to FLAG
# impossible numbers, never to scale them.
PEAK_TFLOPS = {"tpu": 197.0, "cpu": 1.0}
PEAK_GBPS = {"tpu": 819.0, "cpu": 50.0}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--size", type=int, default=512)
    ap.add_argument("--batches", type=int, nargs="+", default=[1, 2, 4, 8])
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--bf16-params", action="store_true")
    ap.add_argument("--out", default="PERF_AUDIT.json")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    devices = devices_with_timeout(900)
    platform = devices[0].platform
    print(f"platform={platform}", flush=True)

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.models import build_model

    cfg = get_config(args.config)
    model = build_model(cfg)
    rng = np.random.default_rng(0)

    init_img = jnp.zeros((1, args.size, args.size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), init_img, train=False)
    if args.bf16_params:
        from improved_body_parts_tpu.utils import bf16_params
        variables = bf16_params(variables)

    def forward(v, x):
        return model.apply(v, x, train=False)[-1][0]

    report = {"platform": platform, "config": args.config, "size": args.size,
              "iters": args.iters, "repeats": args.repeats,
              "bf16_params": args.bf16_params, "batches": {}}

    def flush():
        with open(args.out, "w") as f:
            strict_dump(report, f, indent=2)

    for b in args.batches:
        x = jnp.asarray(
            rng.uniform(0, 1, (b, args.size, args.size, 3)), jnp.float32)
        lowered = jax.jit(forward).lower(variables, x)
        compiled = lowered.compile()
        ca = compiled.cost_analysis() or {}
        gflops = float(ca.get("flops", 0.0)) / 1e9
        gbytes = float(ca.get("bytes accessed", 0.0)) / 1e9

        out = compiled(variables, x)
        jax.block_until_ready(out)
        assert np.isfinite(np.asarray(out, np.float32)).all(), \
            f"non-finite outputs at batch {b}"

        # throughput: R repeats of N pipelined dispatches, block at each
        # repeat end.  Protocol v2: a host-fed counter perturbs one input
        # pixel so no two dispatches are bit-identical — round 5 caught a
        # cache behind the relay serving repeated identical dispatches at
        # 2× the chip's physical peak FLOP rate.  (Independent distinct
        # dispatches can still fan across a pooled relay, so this mode
        # stays the optimistic bound; chained_fps is the honest claim.)
        perturbed = jax.jit(  # graftlint: disable=JGL003 -- one compile per batch size is inherent here: each b is a distinct input shape, and the audit measures exactly those programs
            lambda v, xx, k: forward(v, xx.at[..., :1, :1, :].add(k * 1e-3)))
        out = perturbed(variables, x, np.float32(0))
        jax.block_until_ready(out)
        reps = []
        kk = 1
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = perturbed(variables, x, np.float32(kk))
                kk += 1
            jax.block_until_ready(out)
            reps.append((time.perf_counter() - t0) / args.iters)
        med = statistics.median(reps)
        best = min(reps)

        # latency: defeat pipelining — each next input depends on the
        # previous output through a scalar, so steps serialize (the shared
        # utils.profiling.chained_time protocol)
        from improved_body_parts_tpu.utils import chained_time

        lat = chained_time(forward, variables, x, iters=args.iters)

        fps_med, fps_best = b / med, b / best
        tflops = gflops / 1e3 / med if gflops else None
        gbps = gbytes / med if gbytes else None
        flags = []
        if tflops and tflops > PEAK_TFLOPS.get(platform, 1e9):
            flags.append(f"IMPLIED {tflops:.0f} TFLOP/s EXCEEDS PEAK")
        if gbps and gbps > PEAK_GBPS.get(platform, 1e9):
            flags.append(f"IMPLIED {gbps:.0f} GB/s EXCEEDS PEAK HBM BW")
        entry = {
            "hlo_gflops_per_step": round(gflops, 1),
            "hlo_gbytes_per_step": round(gbytes, 3),
            "throughput_fps_median": round(fps_med, 2),
            "throughput_fps_best": round(fps_best, 2),
            "repeat_spread_ms": [round(r * 1e3, 3) for r in sorted(reps)],
            "chained_latency_ms": round(lat * 1e3, 3),
            "chained_fps": round(b / lat, 2),
            "implied_tflops": round(tflops, 1) if tflops else None,
            "implied_hbm_gbps": round(gbps, 1) if gbps else None,
            "flags": flags,
        }
        report["batches"][b] = entry
        flush()
        print(f"batch {b}: {fps_med:.1f} fps med ({fps_best:.1f} best, "
              f"{b / lat:.1f} chained) | {gflops:.0f} GFLOP/step -> "
              f"{tflops or 0:.1f} TFLOP/s, {gbps or 0:.0f} GB/s {flags}",
              flush=True)

    print(strict_dumps(report))


if __name__ == "__main__":
    main()
