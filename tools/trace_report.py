#!/usr/bin/env python
"""Fold a run's span trace into a ``.perfetto.json`` + critical-path text.

Input is the Chrome ``trace_event`` JSON that ``obs.trace.TraceRecorder``
exports (``RunTelemetry(trace_path=...)`` writes it at close; the
``trace_export`` event in the run's JSONL stream points at it).  This
tool:

- validates the events structurally (every record needs name/ph/ts/
  pid/tid; complete spans need a non-negative ``dur``) and refuses a
  file with no valid events — a truncated or hand-damaged trace should
  fail loudly here, not render as an empty mystery in the UI;
- auto-discovers per-worker trace shards next to the input
  (``<trace>.pN`` — what ``ProcessRouter(trace_path=...)`` hands each
  worker process) and STITCHES them into the parent timeline: every
  shard's events are rebased onto the parent's clock axis via the
  ``t0_mono`` anchor both exports carry (CLOCK_MONOTONIC is
  system-wide on Linux, so the shift is exact, not estimated), the
  shard's process is renamed ``serve worker N``, and the router's
  ``cat="proc"`` flow ids line up with the workers' — one request
  renders as ONE arc: router submit → worker serve → router deliver.
  A shard with no ``t0_mono`` anchor cannot be placed and is skipped
  loudly;
- writes a normalized ``<input>.perfetto.json`` (events sorted
  parent-before-child) that loads directly at https://ui.perfetto.dev
  or ``chrome://tracing``;
- prints a text critical-path summary so the common questions — where
  did the wall time go, which phase dominated the step windows, what
  did serving's fan-in look like — are answered without opening a UI:

  - per-span-name aggregates (count, total/mean/max);
  - the step-window account: data-wait vs compute totals and the same
    >=40%-wait input-bound verdict ``tools/telemetry_report.py`` uses;
  - serve requests: count, latency spread from the async begin/end
    pairs, mean batch occupancy from the ``execute`` spans.

    python tools/trace_report.py checkpoints/trace.json
    python tools/trace_report.py trace.json --json summary.json
"""
import argparse
import glob
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import strict_dump  # noqa: E402

# ONE threshold for both reporting tools (imported, not copied — see
# obs.registry): the fraction of the attributed split spent waiting on
# data above which the run is input-bound
from improved_body_parts_tpu.obs.registry import INPUT_BOUND_FRAC  # noqa: E402

_REQUIRED = ("name", "ph", "ts", "pid", "tid")


def _load_events(path):
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        events = data.get("traceEvents")
        other = data.get("otherData", {})
    else:
        events, other = data, {}
    if not isinstance(events, list):
        raise SystemExit(f"{path}: no traceEvents array — not a Chrome "
                         "trace_event file")
    valid, invalid = [], 0
    for ev in events:
        if not isinstance(ev, dict):
            invalid += 1
            continue
        if ev.get("ph") == "M":  # metadata: no timestamp by spec
            if "name" not in ev or "pid" not in ev:
                invalid += 1
                continue
        elif any(k not in ev for k in _REQUIRED) or (
                ev["ph"] == "X" and ev.get("dur", -1) < 0):
            invalid += 1
            continue
        valid.append(ev)
    if not valid:
        raise SystemExit(f"{path}: 0 structurally valid trace events "
                         f"({invalid} invalid) — refusing to report")
    return valid, invalid, other


def discover_shards(path):
    """Per-worker trace shards next to ``path``: ``<path>.p1``,
    ``<path>.p2``, ... (the ``ProcessRouter`` → worker ``trace_path``
    naming).  Globbed, not probed consecutively: a SIGKILLed worker
    never flushes its shard, and that hole must not hide the survivors'
    shards.  Returns existing paths sorted by worker number."""
    out = []
    for p in glob.glob(glob.escape(path) + ".p*"):
        suffix = p[len(path) + 2:]
        if suffix.isdigit():
            out.append((int(suffix), p))
    return [p for _, p in sorted(out)]


def stitch_shards(parent_other, shard_paths):
    """Load worker trace shards and rebase them onto the parent's
    timeline axis.

    Both exports carry ``otherData.t0_mono`` — the absolute
    CLOCK_MONOTONIC anchor their relative timestamps count from.  The
    clock is system-wide, so ``(t0_shard - t0_parent)`` microseconds is
    the EXACT shift that places a worker span among the router's spans
    (no correlation or estimation step).  Returns ``(events, infos)``:
    the rebased shard events (metadata process names rewritten to
    ``serve worker N``) and one info dict per shard for the summary.
    """
    t0_parent = parent_other.get("t0_mono")
    events, infos = [], []
    for path in shard_paths:
        # worker number from the .pN suffix (worker_idx + 1)
        n = int(path.rsplit(".p", 1)[1])
        try:
            sh_events, sh_invalid, sh_other = _load_events(path)
        except SystemExit as e:
            print(f"warning: skipping trace shard {path}: {e}",
                  file=sys.stderr)
            continue
        t0_shard = sh_other.get("t0_mono")
        if t0_parent is None or t0_shard is None:
            print(f"warning: skipping trace shard {path}: no t0_mono "
                  "anchor on "
                  + ("both exports" if t0_parent is None else "the shard")
                  + " — cannot place it on the parent's axis",
                  file=sys.stderr)
            continue
        shift_us = (float(t0_shard) - float(t0_parent)) * 1e6
        for ev in sh_events:
            ev = dict(ev)
            if ev["ph"] == "M":
                if ev["name"] == "process_name":
                    ev["args"] = {"name": f"serve worker {n - 1}"}
            else:
                ev["ts"] = round(ev["ts"] + shift_us, 3)
            events.append(ev)
        infos.append({"path": path, "worker": n - 1,
                      "events": len(sh_events),
                      "invalid": sh_invalid,
                      "shift_ms": round(shift_us / 1e3, 3),
                      "dropped_events": int(
                          sh_other.get("dropped_events", 0))})
    return events, infos


def _verdict(wait_frac):
    """EXACTLY tools/telemetry_report.py's three-way reading of the same
    split — including the mixed band — so the two tools can never
    disagree about one run."""
    if wait_frac >= INPUT_BOUND_FRAC:
        return "input-bound"
    if wait_frac >= INPUT_BOUND_FRAC / 2:
        return "mixed (input pressure)"
    return "compute-bound"


def _track_names(events):
    return {ev.get("tid"): ev.get("args", {}).get("name", str(ev.get("tid")))
            for ev in events
            if ev["ph"] == "M" and ev["name"] == "thread_name"
            and "tid" in ev}


def summarize(events, other):
    spans = [e for e in events if e["ph"] == "X"]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s["dur"] / 1e3)  # ms
    names = {
        name: {"count": len(ds), "total_ms": round(sum(ds), 3),
               "mean_ms": round(statistics.fmean(ds), 3),
               "max_ms": round(max(ds), 3)}
        for name, ds in sorted(by_name.items(),
                               key=lambda kv: -sum(kv[1]))}

    windows = by_name.get("step_window", [])
    wait = sum(by_name.get("data_wait", []))
    hold = sum(by_name.get("compute", []))
    split = wait + hold
    verdict = None
    if split > 0:
        verdict = _verdict(wait / split)

    # serve lifecycle: latency from async begin/end pairs keyed by id,
    # occupancy from the execute spans' batch args
    opened, lat_ms = {}, []
    for e in events:
        if e.get("cat") == "serve" and e["name"] == "request":
            if e["ph"] == "b":
                opened[e.get("id")] = e["ts"]
            elif e["ph"] == "e" and e.get("id") in opened:
                lat_ms.append((e["ts"] - opened.pop(e["id"])) / 1e3)
    # cross-process flow arcs (router submit → worker serve → router
    # deliver): a stitched fleet timeline shows starts ≈ steps ≈
    # finishes; steps at 0 with starts present means the worker shards
    # were NOT stitched (or workers ran with telemetry off)
    flows = {"s": 0, "t": 0, "f": 0}
    for e in events:
        if e.get("cat") == "proc" and e["ph"] in flows:
            flows[e["ph"]] += 1
    proc_flows = ({"starts": flows["s"], "steps": flows["t"],
                   "finishes": flows["f"]}
                  if any(flows.values()) else None)

    batches = [e.get("args", {}).get("batch") for e in spans
               if e["name"] == "execute"]
    batches = [b for b in batches if b]
    serve = None
    if lat_ms or batches:
        serve = {
            "requests": len(lat_ms) + len(opened),
            "unfinished": len(opened),
            "latency_ms": ({"mean": round(statistics.fmean(lat_ms), 3),
                            "max": round(max(lat_ms), 3)}
                           if lat_ms else None),
            "execute_batches": len(batches),
            "mean_batch_occupancy": (round(statistics.fmean(batches), 3)
                                     if batches else None),
        }

    return {
        "events": len(events),
        "spans": len(spans),
        "dropped_events": int(other.get("dropped_events", 0)),
        # a lossy ring means every aggregate below UNDERCOUNTS — the
        # same signal rides the registry as trace_spans_dropped_total
        # so a live scrape sees it too (obs.trace.attach_registry)
        "lossy": bool(int(other.get("dropped_events", 0))),
        "tracks": sorted(_track_names(events).values()),
        "by_name": names,
        "step_windows": {
            "count": len(windows),
            "total_ms": round(sum(windows), 3),
            "data_wait_ms": round(wait, 3),
            "compute_ms": round(hold, 3),
            "data_wait_frac": (round(wait / split, 4) if split else None),
        },
        "verdict": verdict,
        "serve": serve,
        "proc_flows": proc_flows,
    }


def render_text(summary):
    lines = [f"trace: {summary['spans']} spans / {summary['events']} "
             f"events on {len(summary['tracks'])} tracks"]
    if summary["lossy"]:
        lines.append(
            f"WARNING: LOSSY TRACE — the ring dropped "
            f"{summary['dropped_events']} events past capacity; every "
            f"total below undercounts.  Raise TraceRecorder(capacity=) "
            f"(and watch trace_spans_dropped_total on /metrics).")
    sw = summary["step_windows"]
    if sw["count"]:
        lines.append(
            f"step windows: {sw['count']}  data_wait "
            f"{sw['data_wait_ms']:.1f} ms  compute "
            f"{sw['compute_ms']:.1f} ms  wait_frac "
            f"{sw['data_wait_frac']:.0%}" if sw["data_wait_frac"]
            is not None else f"step windows: {sw['count']}")
    if summary["verdict"]:
        lines.append(f"verdict: {summary['verdict']}")
    if summary["serve"]:
        sv = summary["serve"]
        lines.append(
            f"serve: {sv['requests']} requests over "
            f"{sv['execute_batches']} batches"
            + (f", mean occupancy {sv['mean_batch_occupancy']}"
               if sv["mean_batch_occupancy"] else "")
            + (f", latency mean {sv['latency_ms']['mean']:.1f} ms "
               f"max {sv['latency_ms']['max']:.1f} ms"
               if sv["latency_ms"] else ""))
    if summary.get("shards"):
        lines.append(
            "stitched worker shards: "
            + ", ".join(f"worker {s['worker']} ({s['events']} ev, "
                        f"shift {s['shift_ms']:+.1f} ms)"
                        for s in summary["shards"]))
    if summary.get("proc_flows"):
        pf = summary["proc_flows"]
        lines.append(
            f"cross-process flow arcs: {pf['starts']} submits → "
            f"{pf['steps']} worker serves → {pf['finishes']} delivers")
    lines.append("critical path (total span time, desc):")
    for name, st in list(summary["by_name"].items())[:10]:
        lines.append(f"  {name:<14} {st['total_ms']:>10.1f} ms  "
                     f"x{st['count']}  mean {st['mean_ms']:.2f}  "
                     f"max {st['max_ms']:.2f}")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="trace JSON written by RunTelemetry "
                    "(see the run's trace_export event)")
    ap.add_argument("--out", default=None,
                    help="normalized Perfetto output path (default: "
                         "<trace>.perfetto.json)")
    ap.add_argument("--json", default=None,
                    help="also write the summary dict to this path")
    ap.add_argument("--no-shards", action="store_true",
                    help="do not auto-discover/stitch <trace>.pN worker "
                         "shards")
    args = ap.parse_args()

    events, invalid, other = _load_events(args.trace)
    if invalid:
        print(f"warning: dropped {invalid} structurally invalid events",
              file=sys.stderr)
    shard_infos = []
    if not args.no_shards:
        shard_events, shard_infos = stitch_shards(
            other, discover_shards(args.trace))
        events = events + shard_events
        if shard_infos:
            # the lossy flag must see the WHOLE stitched timeline
            other = dict(other)
            other["dropped_events"] = (
                int(other.get("dropped_events", 0))
                + sum(s["dropped_events"] for s in shard_infos))
    # parent-before-child: ts ascending, longer span first on ties
    body = sorted((e for e in events if e["ph"] != "M"),
                  key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    meta = [e for e in events if e["ph"] == "M"]
    # suffix-strip only a TRAILING .json: multi-process traces are named
    # trace.json.pN (tools/train.py), and rsplit would collapse every
    # process's default output onto the lead host's file
    stem = args.trace[:-5] if args.trace.endswith(".json") else args.trace
    out_path = args.out or stem + ".perfetto.json"
    with open(out_path, "w") as f:
        strict_dump({"traceEvents": meta + body, "displayTimeUnit": "ms",
                     "otherData": other}, f)

    summary = summarize(events, other)
    summary["shards"] = shard_infos
    summary["perfetto"] = out_path
    if args.json:
        with open(args.json, "w") as f:
            strict_dump(summary, f, indent=2)
    print(render_text(summary))
    print(f"perfetto export: {out_path} (open at https://ui.perfetto.dev)")


if __name__ == "__main__":
    main()
