#!/usr/bin/env python
"""One-process on-chip TRAINING session: every learn→AP benchmark the
round-4 verdict asked for, under ONE exclusive chip claim.

The chip behind the axon relay is claimed exclusively at first device use
and sequential short-lived claimants have been observed to wedge the pool
(ROADMAP round-4/5 logs) — so, mirroring tools/tpu_session.py for the
measurement suite, this driver runs the TRAINING agenda in one process by
calling the real train/evaluate CLI mains in-process (sys.argv patching),
sequentially, and exits cleanly:

1. ``canonical`` — the reference flagship at FULL resolution
   (synth_canonical_512: 128,998,760 params @512², reference:
   config/config.py:14-16) through the drawn-corpus learn→AP protocol →
   SYNTH_AP_CANONICAL_TPU.json.  CANONICAL_TRAIN.json was the CPU stage
   at reduced canvas; this is the run it staged.
2. ``hard`` — synth_deep on the --hard corpus tier (±60° figure
   rotations, wider scales) → SYNTH_AP_HARD.json, then the TTA grid
   comparison on the SAME trained checkpoint and hard val →
   TTA_HARD.json (the benchmark arm where rotation TTA should pay;
   reference: evaluate.py:89-90).
3. ``ab`` — the seed-replicated A/B matrix tools/ab_summary.py
   aggregates: per seed, synth_deep base (96 img / 10 epochs, big 64-img
   val seed 777) → SWA stage (+5 cyclic-LR epochs) → device-GT twin →
   crowd masked/ablated pair (toy synth config, 48 img / 60 epochs) →
   AB_SUMMARY.json.

Every run writes its artifact immediately; sections skip runs whose
artifact already exists (crash-resumable), and a failed run records the
error and moves on — a scarce chip session never discards earlier work.

    python tools/tpu_train_session.py                  # full agenda
    python tools/tpu_train_session.py --sections ab    # one section
    JAX_PLATFORMS=cpu python tools/tpu_train_session.py --smoke  # CPU smoke

Exit codes: 0 = agenda done (individual runs may still have recorded
errors), 3 = backend bind timed out (wedged claim — retry later).
"""
import argparse
import contextlib
import gc
import io
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
sys.path.insert(0, REPO)
sys.path.insert(0, TOOLS)

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

BIND_TIMEOUT_S = 420


def _call_main(module, argv):
    """Run a CLI module's main() in-process with a patched argv."""
    saved = sys.argv
    sys.argv = [f"{module.__name__}.py"] + [str(a) for a in argv]
    try:
        module.main()
    finally:
        sys.argv = saved
        gc.collect()  # drop device buffers (params/opt state) between runs


def _call_eval(module, argv, cwd):
    """evaluate.py main() in-process, stdout captured for the AP line.

    cwd matters: the detection dump lands under ``results/`` relative to
    the working directory (same contract as synth_ap's subprocess evals).
    """
    buf = io.StringIO()
    saved_cwd = os.getcwd()
    os.chdir(cwd)
    try:
        with contextlib.redirect_stdout(buf):
            _call_main(module, argv)
    finally:
        os.chdir(saved_cwd)
    return buf.getvalue()


class Session:
    def __init__(self, args):
        self.args = args
        self.summary = {"sections": {}, "platform": None}
        import train as train_cli          # tools/train.py
        import evaluate as evaluate_cli    # tools/evaluate.py
        import tta_bench as tta_cli        # tools/tta_bench.py
        from synth_ap import parse_ap, _save_fresh_checkpoint_impl
        self.train_cli = train_cli
        self.evaluate_cli = evaluate_cli
        self.tta_cli = tta_cli
        self.parse_ap = parse_ap
        self.make_fresh = _save_fresh_checkpoint_impl

    def flush(self):
        with open(self.args.session_out, "w") as f:
            strict_dump(self.summary, f, indent=2)

    def art(self, name):
        """Artifact filename; --smoke runs get a SMOKE_ prefix so a later
        REAL session never skip-resumes over 1-epoch CPU smoke numbers."""
        return ("SMOKE_" + name) if self.args.smoke else name

    def try_run(self, out, **kw):
        """Run-level error isolation: one failed run records its error and
        the section moves on (the module docstring's contract)."""
        try:
            return self.synth_run(out, **kw)
        except (Exception, SystemExit) as e:  # noqa: BLE001
            import traceback
            traceback.print_exc()
            self.summary.setdefault("run_errors", {})[out] = (
                f"{type(e).__name__}: {e}")
            self.flush()
            return None

    # ---- one learn→AP run ------------------------------------------------
    def synth_run(self, out, *, config, epochs, canvas, train_images=96,
                  val_images=24, people=2, seed=0, val_seed=12345,
                  crowd=False, hard=False, mask_extras=True, device_gt=0,
                  lr=0.0, workdir=None, fresh_baseline=True,
                  swa_from=None, swa_epochs=5, swa_freq=5, base_artifact=None,
                  keep_last_n=0, milestone_every=0):
        """Mirror of tools/synth_ap.py's protocol, in-process.

        ``swa_from`` = an existing run's workdir: continue its checkpoint
        through the SWA stage (tools/swa_stage.py protocol) instead of
        training from scratch.
        """
        if os.path.exists(out) and not self.args.force:
            print(f"[skip] {out} exists", flush=True)
            return json.load(open(out))
        from improved_body_parts_tpu.config import get_config
        from improved_body_parts_tpu.data import build_fixture, build_val_set
        from improved_body_parts_tpu.train.checkpoint import latest_checkpoint

        cfg = get_config(config)
        boxsize = cfg.skeleton.height
        work = os.path.abspath(workdir or swa_from or os.path.join(
            self.args.work_root, os.path.splitext(os.path.basename(out))[0]))
        os.makedirs(work, exist_ok=True)
        corpus = os.path.join(work, "train_drawn.h5")
        val_dir = os.path.join(work, "val")
        anno = os.path.join(work, "person_keypoints_synth.json")
        ckpt_dir = os.path.join(work, "ckpt")

        # pin the corpus parameters in the workdir: a rerun with different
        # args must not silently reuse data built with the old ones while
        # stamping the artifact with the new (dist_drive's pinning rule)
        pin = {"config": config, "train_images": train_images,
               "val_images": val_images, "people": people,
               "canvas": list(canvas), "seed": seed, "val_seed": val_seed,
               "crowd": crowd, "hard": hard, "mask_extras": mask_extras}
        pin_path = os.path.join(work, "fixture_params.json")
        if not os.path.exists(corpus):
            n_rec = build_fixture(corpus, num_images=train_images,
                                  people_per_image=people, img_size=canvas,
                                  image_size=boxsize, seed=seed, drawn=True,
                                  crowd=crowd, hard=hard,
                                  mask_extras=mask_extras)
            with open(pin_path, "w") as f:
                strict_dump(pin, f)
        else:
            assert os.path.exists(pin_path) and json.load(
                open(pin_path)) == pin, (
                f"workdir {work} holds a corpus built with different "
                f"parameters; use a fresh --work-root")
            import h5py
            with h5py.File(corpus, "r") as f:
                n_rec = len(f["dataset"])
        if not os.path.exists(anno):
            n_val = build_val_set(val_dir, anno, num_images=val_images,
                                  people_per_image=people, img_size=canvas,
                                  image_size=boxsize, seed=val_seed,
                                  drawn=True, crowd=crowd, hard=hard)
        else:
            n_val = None
        print(f"[run] {out}: corpus {n_rec} records, training {config} "
              f"{'SWA +' if swa_from else ''}{swa_epochs if swa_from else epochs}"
              f" epochs on {self.summary['platform']}", flush=True)

        # per-run loss provenance: train.py APPENDS to the workdir's epoch
        # log.  A non-SWA run owns its whole epoch range (a crash-resume
        # CONTINUES the same logical run, so pre-crash epochs belong in
        # its curve); an SWA stage reuses the base arm's workdir and
        # slices at the base epoch count.  Parsing is epoch-keyed with
        # last-occurrence-wins (dist_drive.epoch_losses) — line counts
        # are unreliable (leading-newline format) and a crash between
        # the log line and the checkpoint write duplicates an epoch.
        from dist_drive import epoch_losses
        pre_epochs = 0

        t0 = time.time()
        if swa_from:
            pre_swa = latest_checkpoint(ckpt_dir)
            # --resume auto with an empty dir silently trains from
            # scratch — which would score 5-epoch scratch weights as
            # "SWA" and feed a bogus delta into AB_SUMMARY
            assert pre_swa, (
                f"SWA stage needs the base arm's checkpoint under "
                f"{ckpt_dir}; run the base arm first (same --work-root)")
            latest_epochs = (int(os.path.basename(pre_swa).split("_")[1])
                             + 1)
            # base_marker records the base arm's epoch count BEFORE any
            # SWA epoch trains (so a mid-stage crash still knows the
            # boundary); done_marker records stage completion.  Without
            # them a re-entry would compound MORE cyclic-LR epochs onto
            # the averaged run while reporting a fresh stage.
            base_marker = os.path.join(work, "swa_base_epochs")
            done_marker = os.path.join(work, "swa_stage_done")
            if os.path.exists(base_marker):
                base_epochs = int(open(base_marker).read())
            else:
                base_epochs = latest_epochs
                with open(base_marker, "w") as f:
                    f.write(str(base_epochs))
            pre_epochs = base_epochs
            if os.path.exists(done_marker):
                print(f"[resume] {out}: SWA stage already trained, "
                      "skipping to eval", flush=True)
                pre_swa = None  # latest IS the SWA ckpt; drop the guard
            else:
                # a mid-stage crash leaves intermediate SWA checkpoints:
                # train only the REMAINING epochs (train.py's --epochs is
                # additional after a resume)
                additional = swa_epochs - (latest_epochs - base_epochs)
                if additional <= 0:
                    # crash fell between the final SWA checkpoint save
                    # and the done-marker write: the stage IS complete
                    # and pre_swa already points at the SWA checkpoint
                    pre_swa = None
                if additional > 0:
                    # train.py's SWA loop checkpoints every swa_freq
                    # epochs (plus a final trailing-epoch save when the
                    # stage length is not a freq multiple); clamping the
                    # cadence to the stage keeps the averaging windows
                    # meaningful for short stages
                    swa_freq = min(swa_freq, additional)
                    self._train([
                        "--config", config, "--swa", "--resume", "auto",
                        "--epochs", additional, "--swa-freq", swa_freq,
                        "--train-h5", corpus, "--checkpoint-dir", ckpt_dir,
                        "--workers", 0, "--seed", seed])
                with open(done_marker, "w") as f:
                    f.write("1")
        else:
            # crash-resume INSIDE a run: a rerun after a crash between
            # training and artifact write continues from the last
            # checkpoint instead of retraining from scratch (train.py's
            # --epochs is ADDITIONAL after a resume)
            done = latest_checkpoint(ckpt_dir)
            additional = epochs
            resume_args = []
            # training parameters get their own pin (separate from the
            # corpus pin, which the SWA arm shares): a crash-resume must
            # not continue a checkpoint trained under different
            # epochs/lr/device_gt while stamping the artifact with the
            # new values
            tpin = {"epochs": epochs, "lr": lr, "device_gt": device_gt,
                    "config": config}
            tpin_path = os.path.join(work, "train_params.json")
            if done:
                assert os.path.exists(tpin_path) and json.load(
                    open(tpin_path)) == tpin, (
                    f"{ckpt_dir} holds a run trained under different "
                    f"parameters than {tpin}; use a fresh --work-root")
                done_epochs = int(os.path.basename(done).split("_")[1]) + 1
                additional = epochs - done_epochs
                resume_args = ["--resume", "auto"]
                print(f"[resume] {out}: {done_epochs} epochs done, "
                      f"{max(additional, 0)} to go", flush=True)
            else:
                with open(tpin_path, "w") as f:
                    strict_dump(tpin, f)
            if additional > 0:
                argv = (["--config", config, "--epochs", additional,
                         "--train-h5", corpus, "--checkpoint-dir", ckpt_dir,
                         "--workers", 0, "--print-freq", 20,
                         "--seed", seed] + resume_args)
                if lr:
                    argv += ["--lr", lr]
                if device_gt:
                    argv += ["--device-gt", device_gt]
                # retention GC for the big-state runs (the 512² flagship
                # checkpoint is ~1.5 GB/epoch): keep last-N + best +
                # milestones; crash-resume is unaffected — it counts from
                # the latest checkpoint (always kept) and the append-only
                # epoch log, and GC only ever deletes COMMITTED dirs
                if keep_last_n:
                    argv += ["--keep-last-n", keep_last_n]
                if milestone_every:
                    argv += ["--milestone-every", milestone_every]
                self._train(argv)
        train_s = round(time.time() - t0, 1)

        losses = epoch_losses(ckpt_dir)[pre_epochs:]
        latest = latest_checkpoint(ckpt_dir)
        assert latest, f"no checkpoint under {ckpt_dir}"
        if swa_from:
            assert latest != pre_swa, (
                f"SWA stage saved no new checkpoint (latest still "
                f"{latest}); the eval would score the base weights")

        eval_args = ["--config", config, "--anno", anno, "--images", val_dir,
                     "--oks-proxy", "--boxsize", boxsize, "--compact"]
        # distinct dump names keep the SWA arm from clobbering the base
        # arm's detections in the shared workdir
        dump = "swa" if swa_from else "trained"
        ap_trained = self.parse_ap(_call_eval(
            self.evaluate_cli,
            eval_args + ["--checkpoint", latest, "--dump-name", dump],
            cwd=work))
        ap_fresh = None
        if fresh_baseline and not swa_from:
            fresh_dir = os.path.join(work, "ckpt_fresh")
            if not latest_checkpoint(fresh_dir):
                self.make_fresh(config, fresh_dir)
                gc.collect()
            ap_fresh = self.parse_ap(_call_eval(
                self.evaluate_cli,
                eval_args + ["--checkpoint", latest_checkpoint(fresh_dir),
                             "--dump-name", "fresh"],
                cwd=work))

        platform = self.summary["platform"]
        result = {
            "config": config, "train_images": train_images,
            "train_records": n_rec, "val_images": val_images,
            "val_persons": n_val, "people_per_image": people,
            # the SWA stage trains under train.py's cyclic sawtooth
            # (--swa-lr-max 1e-5 -> --swa-lr-min 1e-6), not the config LR
            "lr": ("swa-cyclic-1e-05..1e-06" if swa_from
                   else lr or cfg.train.learning_rate_per_device),
            "canvas": list(canvas), "decode_path": "compact",
            "crowd": crowd, "miss_mask": mask_extras, "device_gt": device_gt,
            "seed": seed, "val_seed": val_seed, "hard": hard,
            "train_platform": platform, "eval_platform": platform,
            "train_wall_s": train_s,
            "train_loss_first": losses[0] if losses else None,
            "train_loss_last": losses[-1] if losses else None,
            "train_loss_curve": losses,
            "checkpoint": latest,
            # the actual platform, not a hardcoded chip claim: CPU-fallback
            # artifacts must not carry accelerator provenance (ADVICE.md)
            "protocol": "drawn-person fixture; held-out val (different "
                        "seed); OKS-proxy evaluator (APCHECK.md); real "
                        "train/evaluate CLI mains in-process under one "
                        f"{platform} session (tools/tpu_train_session.py)",
        }
        if swa_from:
            result.update({"ap_swa": ap_trained, "swa_epochs": swa_epochs,
                           "swa_freq": swa_freq})
            if base_artifact and os.path.exists(base_artifact):
                base = json.load(open(base_artifact))
                result["ap_base"] = base["ap_trained"]
                result["base_artifact"] = os.path.basename(base_artifact)
                result["swa_delta"] = round(ap_trained - base["ap_trained"], 6)
        else:
            result.update({"epochs": epochs, "ap_trained": ap_trained,
                           "ap_untrained": ap_fresh})
        with open(out, "w") as f:
            strict_dump(result, f, indent=2)
        print(f"[done] {out}: AP {ap_trained} (train {train_s}s)", flush=True)
        return result

    def _train(self, argv):
        _call_main(self.train_cli, argv)

    # ---- sections --------------------------------------------------------
    def section(self, name, fn):
        t0 = time.time()
        entry = {"status": "running"}
        self.summary["sections"][name] = entry
        self.flush()
        try:
            fn()
            entry["status"] = "ok"
        except (Exception, SystemExit) as e:  # noqa: BLE001 — scarce
            # session, keep going (SystemExit: the in-process CLI mains
            # raise it for validation failures and argparse errors)
            entry["status"] = "error"
            entry["error"] = f"{type(e).__name__}: {e}"
            import traceback
            traceback.print_exc()
        entry["wall_s"] = round(time.time() - t0, 1)
        self.flush()

    def run_canonical(self):
        a = self.args
        # smoke mode drops to the reduced-canvas CPU config — the 512²
        # flagship takes minutes PER STEP on a 1-core host
        config = "synth_canonical" if a.smoke else "synth_canonical_512"
        canvas = (288, 384) if a.smoke else (768, 1024)
        self.synth_run(
            self.art("SYNTH_AP_CANONICAL_TPU.json"), config=config,
            epochs=a.canonical_epochs, canvas=canvas,
            train_images=a.canonical_images, val_images=24,
            device_gt=8, seed=0, keep_last_n=3, milestone_every=10)

    def run_hard(self):
        a = self.args
        res = self.synth_run(
            self.art("SYNTH_AP_HARD.json"), config="synth_deep",
            epochs=a.hard_epochs, canvas=(384, 512), hard=True, seed=0)
        if os.path.exists(self.art("TTA_HARD.json")) and not a.force:
            return
        work = os.path.join(a.work_root,
                            os.path.splitext(self.art("SYNTH_AP_HARD.json"))[0])
        # crash-resume: the artifact may predate this session (or come
        # from tools/synth_ap.py, which records no checkpoint) — fall
        # back to the session workdir's latest checkpoint
        ckpt = res.get("checkpoint")
        if not ckpt or not os.path.exists(ckpt):
            from improved_body_parts_tpu.train.checkpoint import (
                latest_checkpoint)
            ckpt = latest_checkpoint(os.path.join(work, "ckpt"))
        anno = os.path.join(work, "person_keypoints_synth.json")
        if not ckpt or not os.path.exists(anno):
            print("[skip] TTA_HARD: no checkpoint/val for the existing "
                  "SYNTH_AP_HARD.json (rerun with --force)", flush=True)
            return
        from improved_body_parts_tpu.config import get_config
        _call_main(self.tta_cli, [
            "--config", "synth_deep", "--checkpoint", ckpt,
            "--anno", anno,
            "--images", os.path.join(work, "val"),
            # match SYNTH_AP_HARD's eval protocol: boxsize = the config's
            # input height (tta_bench's 0 default falls through to the
            # 640 COCO default, which would rescale every val person off
            # the trained scale and invalidate the grid comparison)
            "--boxsize", get_config("synth_deep").skeleton.height,
            "--out", self.art("TTA_HARD.json")])

    def run_ab(self):
        a = self.args
        arms = set(a.ab_arms)
        for seed in a.seeds:
            base_out = self.art(f"SYNTH_AP_DEEP_S{seed}.json")
            deep = dict(config="synth_deep", epochs=a.ab_epochs,
                        canvas=(384, 512), val_images=64, val_seed=777,
                        seed=seed, fresh_baseline=False)
            if "base" in arms:
                self.try_run(base_out, **deep)
            # gate SWA on a COMPLETED base artifact: a partial base
            # checkpoint would train "SWA" from the wrong epoch and the
            # poisoned artifact would never self-correct (skip-resume)
            if "swa" in arms and not os.path.exists(base_out):
                print(f"[skip] SWA S{seed}: base artifact {base_out} "
                      "missing/failed", flush=True)
            elif "swa" in arms:
                self.try_run(
                    self.art(f"SYNTH_AP_DEEP_SWA_S{seed}.json"),
                    config="synth_deep",
                    epochs=0, canvas=(384, 512), val_images=64, val_seed=777,
                    seed=seed, fresh_baseline=False,
                    swa_from=os.path.join(a.work_root,
                                          os.path.splitext(base_out)[0]),
                    swa_epochs=a.swa_epochs, base_artifact=base_out)
            if "devgt" in arms:
                self.try_run(self.art(f"SYNTH_AP_DEEP_DEVICEGT_S{seed}.json"),
                             device_gt=8, **deep)
            crowd = dict(config="synth", epochs=a.crowd_epochs,
                         canvas=(192, 256),
                         train_images=48, val_images=64, val_seed=777,
                         seed=seed, crowd=True, fresh_baseline=False)
            if "crowd" in arms:
                self.try_run(self.art(f"SYNTH_AP_CROWD_S{seed}.json"),
                             **crowd)
                self.try_run(
                    self.art(f"SYNTH_AP_CROWD_UNMASKED_S{seed}.json"),
                    mask_extras=False, **crowd)
        if a.smoke:
            # ab_summary's globs match the REAL artifact names; running it
            # here would aggregate real chip data under a SMOKE_ label
            print("[skip] AB_SUMMARY in smoke mode", flush=True)
            return
        import ab_summary
        _call_main(ab_summary, ["--dir", ".", "--out", "AB_SUMMARY.json"])


def main():
    ap = argparse.ArgumentParser(description="one-process TPU train session")
    ap.add_argument("--sections", nargs="+",
                    default=["canonical", "hard", "ab"],
                    choices=["canonical", "hard", "ab"])
    ap.add_argument("--seeds", type=int, nargs="+", default=[0, 1, 2])
    ap.add_argument("--ab-arms", nargs="+",
                    default=["base", "swa", "devgt", "crowd"],
                    choices=["base", "swa", "devgt", "crowd"],
                    help="which A/B arms to run (CPU fallback sessions can "
                         "pick just the cheap crowd pair)")
    ap.add_argument("--canonical-epochs", type=int, default=30)
    ap.add_argument("--canonical-images", type=int, default=96)
    ap.add_argument("--hard-epochs", type=int, default=30)
    ap.add_argument("--ab-epochs", type=int, default=10)
    ap.add_argument("--crowd-epochs", type=int, default=60)
    ap.add_argument("--swa-epochs", type=int, default=5)
    ap.add_argument("--work-root", default="/tmp/tpu_train_session")
    ap.add_argument("--session-out", default="TPU_TRAIN_SESSION.json")
    ap.add_argument("--force", action="store_true",
                    help="re-run even when the artifact already exists")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny epoch counts for a CPU protocol check")
    args = ap.parse_args()
    if args.smoke:
        # the SMOKE_ prefix covers the session summary too — a CPU
        # protocol check must not overwrite a real session's record
        if args.session_out == "TPU_TRAIN_SESSION.json":
            args.session_out = "SMOKE_TPU_TRAIN_SESSION.json"
        args.canonical_epochs = 1
        args.canonical_images = 8
        args.hard_epochs = 1
        args.ab_epochs = 1
        args.crowd_epochs = 1
        args.swa_epochs = 1
        args.seeds = args.seeds[:1]
    os.makedirs(args.work_root, exist_ok=True)

    from improved_body_parts_tpu.utils import (apply_platform_env,
                                               devices_with_timeout)
    apply_platform_env()
    try:
        devices = devices_with_timeout(60 if args.smoke else BIND_TIMEOUT_S)
    except (RuntimeError, TimeoutError) as e:
        print(f"backend bind failed: {e}", flush=True)
        raise SystemExit(3)

    sess = Session(args)
    sess.summary["platform"] = devices[0].platform
    sess.summary["n_devices"] = len(devices)
    print(f"platform={devices[0].platform} agenda={args.sections}",
          flush=True)
    for name in args.sections:
        sess.section(name, {"canonical": sess.run_canonical,
                            "hard": sess.run_hard,
                            "ab": sess.run_ab}[name])
    sess.flush()
    print(strict_dumps(sess.summary))


if __name__ == "__main__":
    main()
