#!/usr/bin/env python
"""Telemetry-history audit → committed ``HISTORY_AUDIT.json``.

Proves the §7h history layer (``obs.history.HistoryStore`` +
``serve.capacity.CapacityModel`` + the ``/history``/``/query`` routes)
against six gates on a LIVE 2-worker ``ProcessRouter``:

1. **Overhead** — interleaved sampler-ON/sampler-OFF A/B, paired
   per-round overhead, median < ``OVERHEAD_GATE_PCT``%.  Both arms run
   the identical, EXPLICITLY nulled base plane (NullSink +
   NullTraceRecorder + ``telemetry=False`` workers, per the PR 15/18
   estimator discipline — an arm that merely *forgot* to configure
   something measures nothing); the ONLY difference is the ON arm's
   history sampler thread (0.1 s cadence, persistence on) running
   during its slices.
2. **Conservation** — at ON-arm quiescence one forced sample tick must
   agree EXACTLY (==, no tolerance) with the registry and the router's
   own counters: last history sample of ``pool_completed_total`` ==
   registry value == router completed; and the rate integral over the
   raw ring (``Σ rate·dt``) must telescope back to the counter delta.
3. **Gaps** — sampler blackouts (every inter-round stop, plus one
   deliberately injected 4-tick stall) are accounted explicitly: the
   store's gap count == the ``history_gap`` records persisted in the
   shards, and the injected gap reports ≥ 3 missed ticks.  Never
   interpolated, never silently absorbed.
4. **Compiles** — per-arm compile-delta accounting (parent CompileWatch
   + every worker's own counters): 0 post-warmup recompiles per arm.
5. **Routes** — one live ``MetricsServer`` over the ON registry:
   ``/history`` serves the store document, ``/query`` serves raw and
   aggregate reads with ``since=``/``step=``, responses stay bounded
   under ``limit=``, HEAD answers with GET's exact headers and no
   body, malformed params 400, unknown series 404.
6. **Replay** — ``HistoryStore.replay`` over the shards this audit just
   wrote (rotation forced: multiple ``.pN`` shards) reconstructs the
   full derived signal feed — ``signals()``, rates, trends, window
   quantiles, gap accounting, and the fitted ``CapacityModel`` —
   BIT-IDENTICALLY to the values the live store answered.

Plus the capacity fit: a 3-phase load ramp (1→2→4 clients) sampled into
history, fitted into a measured QPS-vs-latency knee and a
``replicas_needed`` answer, committed in the artifact.

    python tools/history_audit.py --rounds 6 --out HISTORY_AUDIT.json
    python tools/history_audit.py --quick        # CI-budget variant
"""
import argparse
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: paired-median throughput overhead the sampler may cost, percent
OVERHEAD_GATE_PCT = 2.0
#: sampler cadence under audit — the production default
#: (``HistoryStore(cadence_s=0.25)``): the overhead claim is for the
#: configuration users actually run, and the A/B slices are sized so
#: each one still spans several live ticks
CADENCE_S = 0.25
#: ticks per shard — small enough that the audit itself exercises
#: rotation and multi-shard replay (the run takes a few dozen ticks)
SHARD_RECORDS = 15

SPEC = "improved_body_parts_tpu.serve.worker:constant_predictor"
#: per-request simulated device time — large enough that the sampler's
#: per-tick cost lands well under the gate, small enough that a round
#: stays sub-second
DELAY_S = 0.003

#: the router registers its pool rollup — ``ProcessRouter.register_into``
#: exports the ServeMetrics family set under the ``pool_`` prefix (plus
#: per-replica ``pool_engine_*``); there is no ``serve_``-prefixed
#: series on this registry
COMPLETED = "pool_completed_total"


def _mk_router(ProcessRouter, *, workers=2, slots=8, delay_s=DELAY_S):
    return ProcessRouter(
        SPEC, num_workers=workers,
        spec_kwargs={"num_parts": 18, "n_people": 2, "delay_s": delay_s},
        slots=slots, max_image_hw=(64, 64), num_parts=18, max_people=8,
        restart_after_s=0.3, probe_interval_s=0.05,
        telemetry=False)


def run_slice(router, images, n_clients, requests):
    """Closed-loop slice: n_clients threads, each ``requests``
    submit→result round-trips; returns imgs/sec."""
    from improved_body_parts_tpu.serve import submit_with_retry

    errs = []

    def work(cid):
        for i in range(requests):
            img = images[(cid + i) % len(images)]
            try:
                fut, _ = submit_with_retry(router.submit, img,
                                           base_s=0.002, max_s=0.05)
                fut.result(timeout=60)
            except Exception as e:  # noqa: BLE001 — surfaced in report
                errs.append(repr(e))
                return
    t0 = time.perf_counter()
    threads = [threading.Thread(target=work, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errs:
        raise SystemExit(f"audit slice failed: {errs[0]}")
    return round(n_clients * requests / wall, 3)


def derived_feed(store, capacity_model):
    """The full derived-signal feed at the store's last tick — computed
    identically against the live store and the replayed one (the
    bit-identity gate compares these two dicts with ==)."""
    return {
        "signals": store.signals(),
        "completed_rate_10s": store.rate(COMPLETED, 10.0),
        "completed_trend_10s": store.trend(COMPLETED, 10.0),
        "queue_depth_quantiles_10s":
            store.window_quantiles("pool_queue_depth", 10.0),
        "completed_rate_integral": store.integrate_rate(COMPLETED),
        "gap_count": store.doc()["gaps"]["count"],
        "gaps_recent": store.doc()["gaps"]["recent"],
        "samples": store.doc()["samples"],
        "series": store.doc()["series"],
        "capacity_fit": capacity_model.to_dict(),
    }


def audit(args):
    import numpy as np

    from improved_body_parts_tpu.obs.events import (
        NullSink, set_sink)
    from improved_body_parts_tpu.obs.history import (
        HistoryStore, discover_history_shards, history_path_for)
    from improved_body_parts_tpu.obs.http import MetricsServer
    from improved_body_parts_tpu.obs.recompile import CompileWatch
    from improved_body_parts_tpu.obs.registry import Registry
    from improved_body_parts_tpu.obs.trace import (
        NullTraceRecorder, set_tracer)
    from improved_body_parts_tpu.serve.capacity import CapacityModel
    from improved_body_parts_tpu.serve.router import ProcessRouter

    workdir = tempfile.mkdtemp(prefix="history_audit_")
    rng = np.random.default_rng(0)
    images = [rng.integers(0, 255, (48, 48, 3), dtype=np.uint8)
              for _ in range(8)]

    # identical, EXPLICITLY nulled base plane on BOTH arms — the A/B
    # isolates the history sampler, nothing else
    set_sink(NullSink())
    set_tracer(NullTraceRecorder())

    reg_on, reg_off = Registry(), Registry()
    watch = CompileWatch(registry=reg_on, sink=NullSink()).install()
    on_router = _mk_router(ProcessRouter)
    on_router.register_into(reg_on)
    on_router.start()
    on_router.warmup([(64, 64)])
    off_router = _mk_router(ProcessRouter)
    off_router.register_into(reg_off)
    off_router.start()
    off_router.warmup([(64, 64)])
    watch.mark_warm("history audit warmup")
    c_warm = int(watch.compiles.value)

    hist_path = history_path_for(os.path.join(workdir, "events.jsonl"))
    store = HistoryStore(reg_on, cadence_s=CADENCE_S,
                         persist_path=hist_path,
                         shard_records=SHARD_RECORDS,
                         run_id="history-audit")
    store.register_into(reg_on)

    # one unmeasured slice per arm: first-touch costs (series creation,
    # shard open, ring growth) are startup, not per-request overhead.
    # Every sampling session is book-ended with one forced tick so even
    # a sub-cadence slice leaves a sample (and its session boundaries
    # leave detectable gaps) — the forced ticks run OUTSIDE the timed
    # windows, so they never touch the A/B
    store.start()
    store.sample_now()
    run_slice(on_router, images, args.clients, args.requests)
    store.sample_now()
    store.stop()
    run_slice(off_router, images, args.clients, args.requests)

    report = {
        "generated_by": "tools/history_audit.py",
        "protocol": {
            "workers": 2, "clients": args.clients,
            "requests_per_client": args.requests,
            "rounds": args.rounds, "predictor_delay_s": DELAY_S,
            "cadence_s": CADENCE_S, "shard_records": SHARD_RECORDS,
            "interleaved": True,
            "arm_order": "alternating per round (A/A-measured ~1.4% "
                         "first-position bias cancels in the paired "
                         "median)",
            "arms": "identical explicitly-nulled base plane (NullSink "
                    "+ NullTraceRecorder + telemetry=False workers); "
                    "ON adds the history sampler thread + persistence, "
                    "OFF runs no HistoryStore at all",
        },
    }

    # ----------------------------------------------- 1: interleaved A/B
    # Two estimator defenses, both calibrated with A/A dry runs
    # (sampler never started) on a 1-core host:
    # - arm order ALTERNATES per round: the A/A measured a ~1.4%
    #   median deficit for whichever arm runs first in a round —
    #   position bias that a fixed on-first order would book as
    #   sampler overhead.  Alternation cancels it in the paired
    #   median.
    # - MANY SHORT rounds instead of few long ones: host noise here is
    #   bursty at the ~100 ms–1 s scale, so with ~1 s slices a burst
    #   lands inside ONE arm of a pair and the per-round delta
    #   inherits its full amplitude (observed spread: same code,
    #   ±5% medians across runs).  With sub-second slices a pair
    #   spans less than the burst, the noise becomes common-mode and
    #   cancels in the delta — and the median gets 3–4× the pairs.
    # The sampler runs for the whole ON slice either way, so the
    # measured quantity (per-second sampling cost) is unchanged.
    on_ips, off_ips = [], []
    arm_compile_delta = {"on": 0, "off": 0}

    def _on_slice():
        store.start()
        store.sample_now()
        c0 = int(watch.compiles.value)
        on_ips.append(run_slice(on_router, images, args.clients,
                                args.requests))
        arm_compile_delta["on"] += int(watch.compiles.value) - c0
        store.sample_now()
        store.stop()

    def _off_slice():
        c0 = int(watch.compiles.value)
        off_ips.append(run_slice(off_router, images, args.clients,
                                 args.requests))
        arm_compile_delta["off"] += int(watch.compiles.value) - c0

    for rnd in range(args.rounds):
        first, second = ((_on_slice, _off_slice) if rnd % 2 == 0
                         else (_off_slice, _on_slice))
        first()
        second()
        print(f"round {rnd}: on {on_ips[-1]} vs off {off_ips[-1]} "
              f"imgs/s ({'on' if rnd % 2 == 0 else 'off'} first)",
              flush=True)
    per_round = [round((off - on) / off * 100.0, 3)
                 for on, off in zip(on_ips, off_ips)]
    median_overhead = round(statistics.median(per_round), 3)
    report["overhead"] = {
        "on_imgs_per_sec": on_ips, "off_imgs_per_sec": off_ips,
        "per_round_overhead_pct": per_round,
        "paired_median_overhead_pct": median_overhead,
        "gate_pct": OVERHEAD_GATE_PCT,
        "ok": bool(median_overhead < OVERHEAD_GATE_PCT),
    }

    # ----------------------------------------------- capacity load ramp
    # sampled phases at 1→2→4 clients: the (qps, latency) spread the
    # capacity model needs a knee from
    ramp_phases = []
    store.start()
    store.sample_now()
    for n_clients in args.ramp:
        ips = run_slice(on_router, images, n_clients, args.ramp_requests)
        store.sample_now()
        ramp_phases.append({"clients": n_clients, "imgs_per_sec": ips})
    store.stop()
    report["ramp"] = ramp_phases

    # ----------------------------------------------- 3: gap accounting
    # deliberate blackout: the sampler is down for 4 cadences (3+
    # missed ticks) and the next tick must mark it — never interpolate
    time.sleep(4 * CADENCE_S)
    store.sample_now()
    gaps = store.doc()["gaps"]
    injected = gaps["recent"][-1] if gaps["recent"] else {}
    persisted_gaps = sum(
        1 for p in discover_history_shards(hist_path)
        for r in _read_events(p) if r.get("event") == "history_gap")
    report["gaps"] = {
        "threshold_s": store.gap_factor * store.cadence_s,
        "detected": gaps["count"],
        "persisted_gap_records": persisted_gaps,
        "injected_last": injected,
        # every detected blackout must be persisted (exact ==) and the
        # injected 0.4 s stall must be marked with its missed-tick
        # count — explicit accounting, never interpolation
        "ok": bool(gaps["count"] >= 1
                   and gaps["count"] == persisted_gaps
                   and injected.get("missed", 0) >= 3),
    }

    # ----------------------------------------------- 2: conservation
    # quiesce (closed-loop clients already joined; depth is 0), force
    # one tick, then all three views must agree EXACTLY
    t_final = store.sample_now()
    reg_val = reg_on.snapshot()[COMPLETED]
    hist_t, hist_val = store.latest(COMPLETED)
    router_completed = float(on_router.metrics.completed)
    raw = store.query(COMPLETED)["points"]
    ring_delta = raw[-1][1] - raw[0][1]
    integral = store.integrate_rate(COMPLETED)
    report["conservation"] = {
        "history_last_sample": hist_val,
        "history_last_t": hist_t,
        "registry_value": reg_val,
        "router_completed": router_completed,
        "rate_integral": integral,
        "ring_counter_delta": ring_delta,
        "ok": bool(hist_t == t_final
                   and hist_val == reg_val == router_completed
                   and abs(integral - ring_delta) < 1e-6),
    }

    # ----------------------------------------------- 4: compile deltas
    worker_recompiles = {
        "on": sum(int(w["recompiles_post_warmup"])
                  for w in on_router.worker_stats()),
        "off": sum(int(w["recompiles_post_warmup"])
                   for w in off_router.worker_stats()),
    }
    report["compiles"] = {
        "parent_warmup_compiles": c_warm,
        "parent_per_arm_delta": arm_compile_delta,
        "worker_recompiles_post_warmup": worker_recompiles,
        "ok": bool(arm_compile_delta["on"] == 0
                   and arm_compile_delta["off"] == 0
                   and worker_recompiles["on"] == 0
                   and worker_recompiles["off"] == 0),
    }

    # ----------------------------------------------- 5: live routes
    import json as _json
    import urllib.error
    import urllib.request

    with MetricsServer(reg_on, history=store) as srv:
        with urllib.request.urlopen(srv.url + "/history", timeout=10) as r:
            hdoc = _json.loads(r.read().decode())
            hist_len = int(r.headers["Content-Length"])
        req = urllib.request.Request(srv.url + "/history", method="HEAD")
        with urllib.request.urlopen(req, timeout=10) as r:
            head_len = int(r.headers["Content-Length"])
            head_body = len(r.read())
        q_url = (srv.url + f"/query?series={COMPLETED}"
                 f"&since={t_final - 30.0}&limit=5")
        with urllib.request.urlopen(q_url, timeout=10) as r:
            qdoc = _json.loads(r.read().decode())
        with urllib.request.urlopen(
                srv.url + f"/query?series={COMPLETED}&step=5",
                timeout=10) as r:
            qagg = _json.loads(r.read().decode())
        codes = {}
        for name, path in (("missing_series", "/query"),
                           ("unknown_series", "/query?series=nope"),
                           ("bad_param",
                            f"/query?series={COMPLETED}&since=zzz")):
            try:
                urllib.request.urlopen(srv.url + path, timeout=10)
                codes[name] = 200
            except urllib.error.HTTPError as e:
                codes[name] = e.code
    report["routes"] = {
        "history_doc_series": hdoc.get("series"),
        "history_doc_samples": hdoc.get("samples"),
        "head_content_length": head_len,
        "get_content_length": hist_len,
        "head_body_bytes": head_body,
        "query_points": len(qdoc.get("points", [])),
        "query_truncated": qdoc.get("truncated"),
        "query_agg_step": qagg.get("step"),
        "error_codes": codes,
        "ok": bool(hdoc.get("series", 0) > 0
                   and head_len == hist_len and head_body == 0
                   and len(qdoc.get("points", [])) <= 5
                   and qdoc.get("truncated") is True
                   and qagg.get("step") == 5.0
                   and codes == {"missing_series": 400,
                                 "unknown_series": 404,
                                 "bad_param": 400}),
    }

    # ----------------------------------------------- capacity fit
    cap = CapacityModel.fit(store, window_s=0.5, replicas=2,
                            prefix="pool")
    need = cap.replicas_needed(
        2.0 * (cap.measured_max_qps or 1.0))
    report["capacity"] = {
        "fit": cap.to_dict(),
        "replicas_needed_2x_max": need,
        "ok": bool(len(cap.points) >= 2
                   and cap.measured_max_qps is not None
                   and (need["replicas"] is not None
                        or need["objective_unmet"])),
    }

    # ----------------------------------------------- 6: replay
    live_feed = derived_feed(store, cap)
    on_router.stop()
    off_router.stop()
    store.close()
    shards = discover_history_shards(hist_path)
    replayed = HistoryStore.replay(hist_path)
    cap_replay = CapacityModel.fit(replayed, window_s=0.5, replicas=2,
                                   prefix="pool")
    replay_feed = derived_feed(replayed, cap_replay)
    mismatched = sorted(k for k in live_feed
                        if live_feed[k] != replay_feed[k])
    report["replay"] = {
        "shards": len(shards),
        "live_feed": live_feed,
        "replay_bit_identical": not mismatched,
        "mismatched_keys": mismatched,
        "ok": bool(len(shards) >= 2 and not mismatched),
    }
    watch.uninstall()

    if not args.keep_workdir:
        shutil.rmtree(workdir, ignore_errors=True)
    else:
        report["workdir"] = workdir

    report["ok"] = bool(all(report[k]["ok"] for k in
                            ("overhead", "conservation", "gaps",
                             "compiles", "routes", "capacity",
                             "replay")))
    return report


def _read_events(path):
    from improved_body_parts_tpu.obs.events import read_events
    return read_events(path)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=24,
                    help="interleaved A/B round pairs (even: arm order "
                         "alternates per round; many short rounds beat "
                         "few long ones — see the loop comment)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=40,
                    help="closed-loop requests per client per round "
                         "(short slices: a round pair spans well under "
                         "a second, so bursty host noise hits both "
                         "arms of the pair and cancels in the delta)")
    ap.add_argument("--ramp", type=int, nargs="+", default=[1, 2, 4],
                    help="client counts for the capacity load ramp")
    ap.add_argument("--ramp-requests", type=int, default=300,
                    help="requests per client per ramp phase")
    ap.add_argument("--quick", action="store_true",
                    help="CI budget: 5 rounds x 50 requests, 2-phase ramp")
    ap.add_argument("--keep-workdir", action="store_true",
                    help="keep the shard workdir for inspection")
    ap.add_argument("--out", default="HISTORY_AUDIT.json")
    args = ap.parse_args()
    if args.quick:
        args.rounds, args.requests = 12, 20
        args.ramp, args.ramp_requests = [1, 4], 120

    report = audit(args)

    from improved_body_parts_tpu.obs.events import strict_dump

    with open(args.out, "w") as f:
        strict_dump(report, f, indent=2, sort_keys=True)
    ov = report["overhead"]
    print(f"overhead: median {ov['paired_median_overhead_pct']}% "
          f"(gate < {ov['gate_pct']}%) "
          f"{'OK' if ov['ok'] else 'FAIL'}")
    cons = report["conservation"]
    print(f"conservation: history {cons['history_last_sample']} == "
          f"registry {cons['registry_value']} == router "
          f"{cons['router_completed']}; integral "
          f"{cons['rate_integral']} vs delta "
          f"{cons['ring_counter_delta']} "
          f"{'OK' if cons['ok'] else 'FAIL'}")
    print(f"gaps: {report['gaps']['detected']} detected == "
          f"{report['gaps']['persisted_gap_records']} persisted "
          f"{'OK' if report['gaps']['ok'] else 'FAIL'}")
    print(f"compiles: {report['compiles']['parent_per_arm_delta']} "
          f"{'OK' if report['compiles']['ok'] else 'FAIL'}")
    print(f"routes: {report['routes']['error_codes']} "
          f"{'OK' if report['routes']['ok'] else 'FAIL'}")
    print(f"capacity: knee {report['capacity']['fit']['knee_qps']} qps "
          f"over {report['capacity']['fit']['windows']} windows "
          f"{'OK' if report['capacity']['ok'] else 'FAIL'}")
    print(f"replay: {report['replay']['shards']} shards, "
          f"bit_identical={report['replay']['replay_bit_identical']} "
          f"{'OK' if report['replay']['ok'] else 'FAIL'}")
    print(f"wrote {args.out}  overall: "
          f"{'OK' if report['ok'] else 'FAIL'}")
    if not report["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
