#!/usr/bin/env python
"""graftlint runner — the repo's static-analysis gate.

    python tools/lint.py                      # lint the configured roots
    python tools/lint.py improved_body_parts_tpu/train
    python tools/lint.py --changed origin/main   # only files that differ
    python tools/lint.py --format json        # machine-readable output
    python tools/lint.py install-hook         # pre-push: both tiers

Exit codes: 0 = no findings at/above ``--fail-on`` (default: error);
1 = findings at/above the threshold; 2 = usage / internal error (a
crash must not read as "clean").

``--changed REF`` lints only tracked files differing from ``REF`` plus
untracked .py files (both intersected with the configured roots) — the
fast pre-PR check on a 150+-file tree.  Rules, severities and roots
come from ``[tool.graftlint]`` in ``pyproject.toml``; suppression is
inline per finding: ``# graftlint: disable=JGL00N -- reason`` (the
reason is mandatory, enforced as JGL000).
"""
import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from improved_body_parts_tpu.analysis import (  # noqa: E402
    GRAFTLINT_VERSION,
    ConfigError,
    all_rules,
    lint_paths,
    load_config,
    ruleset_hash,
)
from improved_body_parts_tpu.analysis.config import SEVERITIES  # noqa: E402


def changed_files(ref, root):
    """Repo-relative .py paths differing from ``ref`` (tracked, minus
    deletions) plus untracked ones."""
    def run(*argv):
        out = subprocess.run(["git", *argv], cwd=root, check=True,
                             capture_output=True, text=True).stdout
        return [p for p in out.split("\0") if p]

    files = run("diff", "--name-only", "-z", "--diff-filter=d", ref, "--")
    files += run("ls-files", "--others", "--exclude-standard", "-z")
    return sorted({f for f in files if f.endswith(".py")})


def scope_to_config(files, config):
    """Keep only files under the configured lint roots."""
    keep = []
    for f in files:
        posix = f.replace(os.sep, "/")
        for p in config.paths:
            if posix == p or posix.startswith(p.rstrip("/") + "/"):
                keep.append(f)
                break
    return keep


#: the pre-push hook `install-hook` writes: both static-analysis tiers
#: run before any PR leaves the machine, with no CI infrastructure —
#: graftlint over the diff (fast), then the program-audit registry
#: sweep at trace level (jaxpr checks + structural fingerprints,
#: ~1 min).  Either tier failing aborts the push.
_PRE_PUSH_HOOK = """\
#!/bin/sh
# installed by `python tools/lint.py install-hook` — both
# static-analysis tiers gate every push (re-run it after pulling a
# newer hook version).
set -e
repo="$(git rev-parse --show-toplevel)"
echo "pre-push: graftlint (changed files vs origin/main)"
"{python}" "$repo/tools/lint.py" --changed origin/main
echo "pre-push: graftaudit registry sweep (trace level)"
"{python}" "$repo/tools/program_audit.py" --level trace
"""


def install_hook(root):
    """Write the repo's ``pre-push`` hook running both analysis tiers.
    Refuses to clobber a hook it did not write.

    The installing interpreter's path is baked into the hook — the
    non-interactive hook shell has no venv activated and stock
    Debian/macOS ship no bare ``python``; ``sys.executable`` is the one
    interpreter known to import this repo's dependencies.  The hooks
    directory comes from ``git rev-parse --git-path hooks`` — the
    directory git actually consults (``--git-dir`` points inside
    ``.git/worktrees/<name>`` in a linked worktree, where hooks never
    run)."""
    hooks_dir = subprocess.run(
        ["git", "rev-parse", "--git-path", "hooks"], cwd=root, check=True,
        capture_output=True, text=True).stdout.strip()
    if not os.path.isabs(hooks_dir):
        hooks_dir = os.path.join(root, hooks_dir)
    hook = os.path.join(hooks_dir, "pre-push")
    if os.path.exists(hook):
        with open(hook, encoding="utf-8") as f:
            existing = f.read()
        if "tools/lint.py" not in existing:
            print(f"graftlint: {hook} exists and was not written by "
                  "install-hook; refusing to overwrite", file=sys.stderr)
            return 2
    os.makedirs(os.path.dirname(hook), exist_ok=True)
    with open(hook, "w", encoding="utf-8") as f:
        f.write(_PRE_PUSH_HOOK.format(python=sys.executable))
    os.chmod(hook, 0o755)
    print(f"installed {hook} (graftlint --changed + graftaudit trace "
          "sweep run before every push)")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="graftlint: this repo's bug classes as lint rules")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: [tool.graftlint] "
                         "paths); the single word `install-hook` "
                         "installs the pre-push hook instead")
    ap.add_argument("--root", default=REPO,
                    help="repo root (pyproject.toml location)")
    ap.add_argument("--changed", metavar="REF",
                    help="lint only files differing from this git ref "
                         "(plus untracked .py files)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--fail-on", choices=SEVERITIES + ("never",),
                    default="error",
                    help="exit 1 when findings at/above this severity "
                         "exist (default: error)")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:20s} [{rule.severity}]  "
                  f"{rule.postmortem}")
        return 0

    if args.paths == ["install-hook"]:
        try:
            return install_hook(args.root)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"graftlint: install-hook: {e}", file=sys.stderr)
            return 2

    try:
        config = load_config(args.root)
    except ConfigError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.changed:
        try:
            files = changed_files(args.changed, args.root)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = e.stderr.strip() if getattr(e, "stderr", None) else e
            print(f"graftlint: --changed {args.changed}: {detail}",
                  file=sys.stderr)
            return 2
        paths = scope_to_config(files, config)
        if args.paths:
            paths = [p for p in paths
                     if any(p == q or p.startswith(q.rstrip("/") + "/")
                            for q in args.paths)]
    else:
        paths = args.paths or list(config.paths)

    result = lint_paths(paths, args.root, config)
    counts = result.counts()

    if args.format == "json":
        print(json.dumps({
            "version": GRAFTLINT_VERSION,
            "ruleset": ruleset_hash(),
            "files": result.files,
            "counts": counts,
            "suppressed": result.suppressed,
            "parse_errors": result.parse_errors,
            "findings": [f.as_dict() for f in result.findings],
        }, indent=2, allow_nan=False))
    else:
        for f in result.findings:
            print(f.format())
        print(f"graftlint {GRAFTLINT_VERSION} (rules {ruleset_hash()}): "
              f"{result.files} files, "
              f"{counts['error']} errors, {counts['warning']} warnings, "
              f"{counts['info']} info, {result.suppressed} suppressed")

    if args.fail_on == "never":
        return 0
    threshold = SEVERITIES.index(args.fail_on)
    bad = sum(n for sev, n in counts.items()
              if SEVERITIES.index(sev) >= threshold)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
