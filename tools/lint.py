#!/usr/bin/env python
"""graftlint runner — the repo's static-analysis gate.

    python tools/lint.py                      # lint the configured roots
    python tools/lint.py improved_body_parts_tpu/train
    python tools/lint.py --changed origin/main   # only files that differ
    python tools/lint.py --format json        # machine-readable output

Exit codes: 0 = no findings at/above ``--fail-on`` (default: error);
1 = findings at/above the threshold; 2 = usage / internal error (a
crash must not read as "clean").

``--changed REF`` lints only tracked files differing from ``REF`` plus
untracked .py files (both intersected with the configured roots) — the
fast pre-PR check on a 150+-file tree.  Rules, severities and roots
come from ``[tool.graftlint]`` in ``pyproject.toml``; suppression is
inline per finding: ``# graftlint: disable=JGL00N -- reason`` (the
reason is mandatory, enforced as JGL000).
"""
import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from improved_body_parts_tpu.analysis import (  # noqa: E402
    GRAFTLINT_VERSION,
    ConfigError,
    all_rules,
    lint_paths,
    load_config,
    ruleset_hash,
)
from improved_body_parts_tpu.analysis.config import SEVERITIES  # noqa: E402


def changed_files(ref, root):
    """Repo-relative .py paths differing from ``ref`` (tracked, minus
    deletions) plus untracked ones."""
    def run(*argv):
        out = subprocess.run(["git", *argv], cwd=root, check=True,
                             capture_output=True, text=True).stdout
        return [p for p in out.split("\0") if p]

    files = run("diff", "--name-only", "-z", "--diff-filter=d", ref, "--")
    files += run("ls-files", "--others", "--exclude-standard", "-z")
    return sorted({f for f in files if f.endswith(".py")})


def scope_to_config(files, config):
    """Keep only files under the configured lint roots."""
    keep = []
    for f in files:
        posix = f.replace(os.sep, "/")
        for p in config.paths:
            if posix == p or posix.startswith(p.rstrip("/") + "/"):
                keep.append(f)
                break
    return keep


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="graftlint: this repo's bug classes as lint rules")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: [tool.graftlint] "
                         "paths)")
    ap.add_argument("--root", default=REPO,
                    help="repo root (pyproject.toml location)")
    ap.add_argument("--changed", metavar="REF",
                    help="lint only files differing from this git ref "
                         "(plus untracked .py files)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--fail-on", choices=SEVERITIES + ("never",),
                    default="error",
                    help="exit 1 when findings at/above this severity "
                         "exist (default: error)")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:20s} [{rule.severity}]  "
                  f"{rule.postmortem}")
        return 0

    try:
        config = load_config(args.root)
    except ConfigError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.changed:
        try:
            files = changed_files(args.changed, args.root)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = e.stderr.strip() if getattr(e, "stderr", None) else e
            print(f"graftlint: --changed {args.changed}: {detail}",
                  file=sys.stderr)
            return 2
        paths = scope_to_config(files, config)
        if args.paths:
            paths = [p for p in paths
                     if any(p == q or p.startswith(q.rstrip("/") + "/")
                            for q in args.paths)]
    else:
        paths = args.paths or list(config.paths)

    result = lint_paths(paths, args.root, config)
    counts = result.counts()

    if args.format == "json":
        print(json.dumps({
            "version": GRAFTLINT_VERSION,
            "ruleset": ruleset_hash(),
            "files": result.files,
            "counts": counts,
            "suppressed": result.suppressed,
            "parse_errors": result.parse_errors,
            "findings": [f.as_dict() for f in result.findings],
        }, indent=2, allow_nan=False))
    else:
        for f in result.findings:
            print(f.format())
        print(f"graftlint {GRAFTLINT_VERSION} (rules {ruleset_hash()}): "
              f"{result.files} files, "
              f"{counts['error']} errors, {counts['warning']} warnings, "
              f"{counts['info']} info, {result.suppressed} suppressed")

    if args.fail_on == "never":
        return 0
    threshold = SEVERITIES.index(args.fail_on)
    bad = sum(n for sev, n in counts.items()
              if SEVERITIES.index(sev) >= threshold)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
