#!/usr/bin/env python
"""List COCO categories / supercategories of an annotation file.

The debugging aid the reference keeps at data/dataset/see_coco_data.py
(hard-coded path removed; pass --anno).

    python tools/list_coco.py --anno annotations/instances_val2017.json
"""
import argparse


def main():
    ap = argparse.ArgumentParser(description="COCO category lister")
    ap.add_argument("--anno", required=True, help="instances_*.json path")
    args = ap.parse_args()

    try:
        from pycocotools.coco import COCO
    except ImportError:
        raise SystemExit("pycocotools is not installed (host-side "
                         "dependency; see SURVEY.md §2.9)")

    coco = COCO(args.anno)
    cats = coco.loadCats(coco.getCatIds())
    print("COCO categories:\n" + " ".join(c["name"] for c in cats) + "\n")
    print("COCO supercategories:\n"
          + " ".join(sorted({c["supercategory"] for c in cats})))


if __name__ == "__main__":
    main()
