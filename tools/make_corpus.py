#!/usr/bin/env python
"""COCO → HDF5 corpus CLI (reference: data/coco_masks_hdf5.py __main__).

    python tools/make_corpus.py --anno annotations/person_keypoints_train2017.json \
        --images train2017 --out-train coco_train_dataset512.h5 \
        --out-val coco_val_dataset512.h5 --image-size 512
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description="build the training corpus")
    ap.add_argument("--anno", required=True)
    ap.add_argument("--images", required=True)
    ap.add_argument("--out-train", required=True)
    ap.add_argument("--out-val", required=True)
    ap.add_argument("--image-size", type=int, default=512)
    ap.add_argument("--val-size", type=int, default=100)
    ap.add_argument("--limit", type=int, default=None)
    args = ap.parse_args()

    from improved_body_parts_tpu.data.hdf5_corpus import build_coco_corpus

    t0 = time.time()
    tr, va = build_coco_corpus(args.anno, args.images, args.out_train,
                               args.out_val, image_size=args.image_size,
                               val_size=args.val_size, limit=args.limit)
    print(f"train records: {tr}, val records: {va} "
          f"({(time.time() - t0) / 60:.1f} min)")


if __name__ == "__main__":
    main()
