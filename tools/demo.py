#!/usr/bin/env python
"""Single-image demo CLI (reference: demo_image.py, ``--image/--output``).

    python tools/demo.py --checkpoint checkpoints/epoch_99 \
        --image person.jpg --output result.jpg
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description="Pose demo")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--image", required=True)
    ap.add_argument("--output", default="result.jpg")
    ap.add_argument("--no-native", action="store_true")
    ap.add_argument("--device-decode", action="store_true",
                    help="fused end-to-end decode: greedy person "
                         "assembly runs ON DEVICE in the same program "
                         "as the forward (tools/evaluate.py's lane); "
                         "an overflowing crowd falls back to the host "
                         "ensemble path and says so")
    ap.add_argument("--boxsize", type=int, default=0,
                    help="scale the image so its height maps to this "
                         "network input size (the reference's INI "
                         "[models] boxsize); 0 keeps the library default")
    ap.add_argument("--params-dtype", default="auto",
                    choices=["auto", "bf16", "fp32"],
                    help="weight storage; auto = bf16 on TPU, fp32 elsewhere")
    args = ap.parse_args()

    from improved_body_parts_tpu.infer.demo import run_demo
    from tools.evaluate import load_predictor

    predictor = load_predictor(args.config, args.checkpoint,
                               boxsize=args.boxsize,
                               params_dtype=args.params_dtype)
    _, (subset, _) = run_demo(predictor, args.image, args.output,
                              use_native=not args.no_native,
                              device_decode=args.device_decode)
    print(f"{len(subset)} people -> {args.output}")


if __name__ == "__main__":
    main()
