#!/usr/bin/env python
"""Cascade-serving benchmark: student-first two-tier serving vs
teacher-only, on a mixed easy/hard synthetic stream.

The claim under test (ROADMAP open item 2): a cheap student lane that
answers easy traffic and escalates hard frames off the fused decode
payload's free signals multiplies served QPS without new hardware and
without giving up the teacher's quality on the frames that need it.

Protocol (the standing interleaved-round discipline of
serve_bench/ckpt_bench — the only perf protocol this host trusts):

- Both tiers run REAL forwards (student = the narrow 1-stack
  ``--student-config``, teacher = ``--teacher-config``) wrapped in a
  flip-aware planted-maps shim (the e2e_bench ``PlantedModel`` idea,
  extended): the input image's brightness selects, PER LANE and on
  device, between an easy planted crowd (``--easy-people``) and a hard
  one (``--hard-people``).  Hard frames therefore decode to a person
  count above the committed ``--max-people`` threshold and the cascade
  escalates exactly them — the escalation decision exercises the real
  signal path end to end, while the decode workload stays
  trained-model-like.
- K closed-loop clients drive a mixed stream (``--hard-frac`` bright
  frames); rounds alternate a cascade slice and a teacher-only slice,
  and the verdict is the MEDIAN per-round QPS ratio (host drift hits
  both arms of a round equally).
- Quality gate: every unique image is decoded once by each arm and
  scored with the OKS AP machinery (``infer.oks.evaluate_oks``) against
  the planted ground truth; the cascade's synthetic AP must be within
  ``--ap-tol`` relative of teacher-only.  Both arms see identical
  planted maps, so this isolates the SERVING layer's claim — escalation
  routing loses nobody; the student-vs-teacher model-quality trade is
  the distillation trainer's domain (tests/test_distill.py), not this
  bench's.
- Warmup precompiles BOTH tiers through the shared predictor-set path;
  the committed artifact asserts 0 post-warmup recompiles across the
  whole sweep (CPU-host caveat: both tiers share the same few cores, so
  the throughput ratio here UNDERSTATES the on-chip win, where the
  student's smaller program frees real accelerator time).

    python tools/cascade_bench.py --out CASCADE_BENCH.json
"""
import argparse
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)

# the stick-figure joint layout shared with e2e_bench.planted_maps
# (relative (dx, dy) offsets of each part from the figure anchor)
_LAYOUT = [("nose", 0, 0.12), ("neck", 0, 0.21), ("Rsho", -0.09, 0.22),
           ("Lsho", 0.09, 0.22), ("Relb", -0.13, 0.33),
           ("Lelb", 0.13, 0.33), ("Rwri", -0.14, 0.43),
           ("Lwri", 0.14, 0.43), ("Rhip", -0.05, 0.45),
           ("Lhip", 0.05, 0.45), ("Rkne", -0.06, 0.59),
           ("Lkne", 0.06, 0.59), ("Rank", -0.06, 0.72),
           ("Lank", 0.06, 0.72), ("Reye", -0.02, 0.10),
           ("Leye", 0.02, 0.10), ("Rear", -0.04, 0.11),
           ("Lear", 0.04, 0.11)]


def plant_people(skeleton, n_people, rng, canvas):
    """Stride-grid maps for N planted stick people PLUS their COCO-order
    ground truth (the quality gate's GT side).  Coordinates are canvas
    pixels — the bench pins ``boxsize == size == canvas`` so decoded
    detections come back in the same space 1:1."""
    import dataclasses

    import numpy as np

    from improved_body_parts_tpu.data.heatmapper import Heatmapper

    sk = dataclasses.replace(skeleton, width=canvas, height=canvas)
    joints = np.zeros((n_people, sk.num_parts, 3), np.float32)
    joints[:, :, 2] = 2
    region = canvas * 0.86
    xs = np.linspace(0.18, 0.82, n_people) * region
    for p in range(n_people):
        cx = xs[p] + rng.uniform(-4, 4)
        scale = rng.uniform(0.42, 0.52) * region
        y0 = rng.uniform(0.02, 0.12) * region
        for name, dx, dy in _LAYOUT:
            joints[p, sk.parts_dict[name]] = [cx + dx * scale,
                                              y0 + dy * scale, 1]
    maps = Heatmapper(sk).create_heatmaps(
        joints, np.ones(sk.grid_shape, np.float32)).astype(np.float32)

    mapping = skeleton.dt_gt_mapping
    gts = []
    for p in range(n_people):
        kp = np.zeros((17, 3), np.float64)
        for di, gi in mapping.items():
            if gi is None:
                continue
            kp[gi] = [joints[p, di, 0], joints[p, di, 1], 2.0]
        xs_v, ys_v = kp[kp[:, 2] > 0, 0], kp[kp[:, 2] > 0, 1]
        area = float((xs_v.max() - xs_v.min()) * (ys_v.max() - ys_v.min()))
        gts.append({"keypoints": kp, "area": max(area, 1.0)})
    return maps, gts


class TieredPlantedModel:
    """Flip-aware planted-maps shim with a PER-LANE difficulty select:
    output = (easy | hard planted maps, chosen by the lane's input-image
    brightness ON DEVICE) + 1e-3 x the real last-stack output — the full
    forward still runs (honest device time for the wrapped tier's real
    architecture), the maps contain decodable people, and hard frames
    carry a crowd the escalation policy fires on.

    Mirror lanes (the second half, in both the 2-lane single and 2N-lane
    batch programs) get the width-flipped, channel-permuted maps so the
    flip-ensemble merge reconstructs the planted people exactly (no
    ghosts, no halving) — the PlantedModel discipline."""

    def __init__(self, model, easy_maps, hard_maps, skeleton,
                 bright_thresh: float = 0.5):
        self.model = model
        self.easy = easy_maps
        self.hard = hard_maps
        self.skeleton = skeleton
        self.bright_thresh = bright_thresh

    def apply(self, variables, imgs, train=False):
        import jax.numpy as jnp

        sk = self.skeleton
        preds = self.model.apply(variables, imgs, train=train)
        out = preds[-1][0]
        gh, gw = out.shape[1], out.shape[2]

        def straight_mirror(maps):
            assert maps.shape[0] >= gh and maps.shape[1] >= gw, (
                "planted canvas smaller than the model grid")
            m = jnp.asarray(maps[:gh, :gw])
            mm = jnp.concatenate(
                [m[..., :sk.paf_layers][..., jnp.asarray(sk.flip_paf_ord)],
                 m[..., sk.heat_start:sk.num_layers]
                 [..., jnp.asarray(sk.flip_heat_ord)]], axis=-1)[:, ::-1]
            return m, mm

        e, em = straight_mirror(self.easy)
        h, hm = straight_mirror(self.hard)
        # brightness is flip-invariant, so lane i and its mirror N+i
        # always agree on the difficulty select
        bright = imgs.mean(axis=(1, 2, 3)) > self.bright_thresh
        n = out.shape[0] // 2
        sel = bright[:, None, None, None]
        straight = jnp.where(sel[:n], h[None], e[None]) + 1e-3 * out[:n]
        mirror = jnp.where(sel[n:], hm[None], em[None]) + 1e-3 * out[n:]
        return [[jnp.concatenate([straight, mirror], axis=0)]]


def make_images(size, n_each, rng):
    """(easy_images, hard_images): dark vs bright BGR uint8 frames —
    the stream's difficulty carrier."""
    import numpy as np

    easy = [rng.integers(0, 50, (size, size, 3)).astype(np.uint8)
            for _ in range(n_each)]
    hard = [rng.integers(205, 255, (size, size, 3)).astype(np.uint8)
            for _ in range(n_each)]
    return easy, hard


def run_clients(n_clients, requests, work_fn):
    latencies = [[] for _ in range(n_clients)]
    errors = []

    def client(cid):
        try:
            for i in range(requests):
                t0 = time.perf_counter()
                work_fn(cid, i)
                latencies[cid].append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — surfaced after join
            errors.append(e)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return wall, [v for lat in latencies for v in lat]


def run_slice(submit, stream, n_clients, requests):
    """One closed-loop slice: ``submit(image) -> Future``; sheds retry
    through the shared policy helper and are reported, not failed."""
    from improved_body_parts_tpu.serve import submit_with_retry

    retries = [0]
    lock = threading.Lock()

    def work(cid, i):
        img = stream[(cid + i * n_clients) % len(stream)]
        fut, n = submit_with_retry(submit, img, base_s=0.002, max_s=0.05)
        if n:
            with lock:
                retries[0] += n
        fut.result()

    wall, lats = run_clients(n_clients, requests, work)
    total = n_clients * requests
    lats.sort()
    return {"imgs_per_sec": round(total / wall, 3),
            "p95_ms": round(lats[int(0.95 * (len(lats) - 1))] * 1e3, 2),
            "shed_retries": retries[0]}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--student-config", default="synth_deep_student",
                    help="fast-tier architecture (the production-shape "
                         "default pairs the 2-stack quarter-width "
                         "student with the 4-stack synth_deep teacher; "
                         "tiny_student/tiny is the seconds-scale smoke "
                         "pair)")
    ap.add_argument("--teacher-config", default="synth_deep")
    ap.add_argument("--size", type=int, default=256,
                    help="frame H=W; also the planted canvas and the "
                         "boxsize, so GT and detections share one "
                         "coordinate space")
    ap.add_argument("--hard-frac", type=float, default=0.25,
                    help="fraction of the stream that is hard (bright "
                         "-> crowd above the escalation threshold)")
    ap.add_argument("--easy-people", type=int, default=2)
    ap.add_argument("--hard-people", type=int, default=6)
    ap.add_argument("--max-people", type=int, default=4,
                    help="EscalationPolicy.max_people — the committed "
                         "threshold between the planted easy and hard "
                         "crowds")
    ap.add_argument("--score-floor", type=float, default=0.0)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=8,
                    help="closed-loop requests per client per slice")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=30.0)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--ap-tol", type=float, default=0.03,
                    help="max relative synthetic-AP deficit of the "
                         "cascade vs teacher-only")
    ap.add_argument("--target-ratio", type=float, default=1.3,
                    help="the QPS claim: median cascade/teacher-only "
                         "round ratio the artifact gates on")
    ap.add_argument("--out", default="CASCADE_BENCH.json")
    args = ap.parse_args()

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import numpy as np

    platform = devices_with_timeout(900)[0].platform
    print(f"platform={platform}", flush=True)

    import jax.numpy as jnp

    from improved_body_parts_tpu.config import (
        InferenceModelParams, get_config)
    from improved_body_parts_tpu.infer import Predictor, evaluate_oks
    from improved_body_parts_tpu.models import build_model
    from improved_body_parts_tpu.obs import Registry, RunTelemetry
    from improved_body_parts_tpu.serve import CascadeEngine, \
        DynamicBatcher, EscalationPolicy, ServeMetrics

    s_cfg = get_config(args.student_config)
    t_cfg = get_config(args.teacher_config)
    assert s_cfg.skeleton == t_cfg.skeleton, \
        "cascade tiers must share the skeleton"
    sk = s_cfg.skeleton
    rng = np.random.default_rng(0)
    size = args.size

    easy_maps, easy_gt = plant_people(sk, args.easy_people, rng, size)
    hard_maps, hard_gt = plant_people(sk, args.hard_people, rng, size)

    def tiered_predictor(cfg):
        model = build_model(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, size, size, 3)), train=False)
        planted = TieredPlantedModel(model, easy_maps, hard_maps, sk)
        return Predictor(planted, variables, sk,
                         model_params=InferenceModelParams(
                             boxsize=size, max_downsample=64),
                         bucket=64)

    student = tiered_predictor(s_cfg)
    teacher = tiered_predictor(t_cfg)

    easy_imgs, hard_imgs = make_images(size, 4, rng)
    # the mixed stream: hard frames spread evenly at --hard-frac
    # (Bresenham interleave, so every client's closed loop sees the mix)
    n_stream = 16
    n_hard = max(1, round(args.hard_frac * n_stream))
    stream, e_i, h_i = [], 0, 0
    for i in range(n_stream):
        if (i + 1) * n_hard // n_stream > i * n_hard // n_stream:
            stream.append(hard_imgs[h_i % len(hard_imgs)])
            h_i += 1
        else:
            stream.append(easy_imgs[e_i % len(easy_imgs)])
            e_i += 1
    hard_in_stream = h_i

    telemetry = RunTelemetry(
        None, registry=Registry(),
        run_meta={"tool": "cascade_bench", "platform": platform})
    policy = EscalationPolicy(max_people=args.max_people,
                              score_floor=args.score_floor)
    batcher_kw = dict(max_batch=args.max_batch,
                      max_wait_ms=args.max_wait_ms,
                      max_queue=args.max_queue)
    sizes = [(size, size)]

    report = {
        "platform": platform,
        "student_config": args.student_config,
        "teacher_config": args.teacher_config,
        "size": size, "hard_frac_requested": args.hard_frac,
        "hard_frac_stream": round(hard_in_stream / n_stream, 3),
        "easy_people": args.easy_people, "hard_people": args.hard_people,
        "policy": {"max_people": args.max_people,
                   "score_floor": args.score_floor,
                   "escalate_on_overflow": True},
        "clients": args.clients, "requests_per_slice":
            args.clients * args.requests, "rounds": args.rounds,
        "max_batch": args.max_batch, "max_wait_ms": args.max_wait_ms,
        "note": "Interleaved rounds, median per-round ratio (the "
                "standing bench protocol). Both tiers run their real "
                "forwards behind a flip-aware planted-maps shim whose "
                "per-lane brightness select makes hard frames decode "
                "to a crowd above the committed max_people threshold; "
                "both tiers plant IDENTICAL maps, so the AP gate "
                "isolates the serving layer (escalation loses nobody) "
                "while the student-vs-teacher model quality trade "
                "belongs to the distillation trainer. CPU-host caveat: "
                "both tiers share the same cores, so the ratio "
                "UNDERSTATES the on-chip win.",
    }

    def flush():
        with open(args.out, "w") as f:
            strict_dump(report, f, indent=2)

    cascade = CascadeEngine.build(student, teacher, policy=policy,
                                  registry=telemetry.registry,
                                  **batcher_kw)
    teacher_only = DynamicBatcher(teacher,
                                  metrics=ServeMetrics(
                                      model="teacher_only"),
                                  registry=telemetry.registry,
                                  device_decode=True, **batcher_kw)
    with cascade, teacher_only:
        warm = cascade.warmup(sizes)
        warm_t = teacher_only.warmup(sizes)
        telemetry.mark_warm("cascade + teacher-only warmup precompile")
        report["warmup"] = {
            "student_newly_compiled": warm["student"]["newly_compiled"],
            "teacher_newly_compiled": warm["teacher"]["newly_compiled"],
            "teacher_only_newly_compiled": warm_t["newly_compiled"]}

        # --- interleaved throughput rounds ---------------------------
        cas_rounds, tea_rounds = [], []
        for r in range(args.rounds):
            cas = run_slice(cascade.submit, stream, args.clients,
                            args.requests)
            tea = run_slice(teacher_only.submit, stream, args.clients,
                            args.requests)
            cas_rounds.append(cas)
            tea_rounds.append(tea)
            print(f"round {r}: cascade {cas['imgs_per_sec']} vs "
                  f"teacher-only {tea['imgs_per_sec']} imgs/s",
                  flush=True)
        # routing snapshot BEFORE the quality pass: the committed
        # escalation rate describes the serving stream, not the
        # half-easy/half-hard unique-image set the AP gate decodes
        snap = cascade.metrics.snapshot()
        # per-hop p50/p95/p99 decomposition per tier (queue/
        # batch_formation/device/decode/deliver, serve.metrics.HOPS)
        # alongside the e2e numbers — same interleaved-round protocol
        report["hops_ms"] = {
            "student": cascade.student.metrics.snapshot()["hops_ms"],
            "teacher": cascade.teacher.metrics.snapshot()["hops_ms"],
            "teacher_only": teacher_only.metrics.snapshot()["hops_ms"]}
        report["hop_conservation_frac"] = {
            "student": cascade.student.metrics.snapshot()[
                "hop_conservation_frac"],
            "teacher_only": teacher_only.metrics.snapshot()[
                "hop_conservation_frac"]}

        # --- quality gate: per-image decode, both arms, OKS AP -------
        gts, det_cascade, det_teacher = {}, {}, {}
        uniq = [(i, im, im.mean() > 127) for i, im in
                enumerate(easy_imgs + hard_imgs)]
        for img_id, im, is_hard in uniq:
            gts[img_id] = hard_gt if is_hard else easy_gt
            det_cascade[img_id] = cascade.submit(im).result(timeout=120)
            det_teacher[img_id] = teacher_only.submit(im).result(
                timeout=120)
        ap_c = evaluate_oks(gts, det_cascade)["AP"]
        ap_t = evaluate_oks(gts, det_teacher)["AP"]
        rel = abs(ap_c - ap_t) / max(ap_t, 1e-9)
        report["quality"] = {
            "cascade_synthetic_ap": round(ap_c, 4),
            "teacher_only_synthetic_ap": round(ap_t, 4),
            "rel_diff": round(rel, 4), "tolerance": args.ap_tol,
            "within_tolerance": bool(rel <= args.ap_tol)}
        flush()
        print(f"quality: cascade AP {ap_c:.4f} vs teacher-only "
              f"{ap_t:.4f} (rel {rel:.4f})", flush=True)

    ratios = sorted(c["imgs_per_sec"] / t["imgs_per_sec"]
                    for c, t in zip(cas_rounds, tea_rounds))
    median_ratio = ratios[len(ratios) // 2]
    report.update({
        "cascade_imgs_per_sec": [r["imgs_per_sec"] for r in cas_rounds],
        "teacher_only_imgs_per_sec": [r["imgs_per_sec"]
                                      for r in tea_rounds],
        "cascade_p95_ms": cas_rounds[-1]["p95_ms"],
        "teacher_only_p95_ms": tea_rounds[-1]["p95_ms"],
        "shed_retries_total": sum(r["shed_retries"]
                                  for r in cas_rounds + tea_rounds),
        "per_round_ratio": [round(r, 3) for r in ratios],
        "median_round_ratio": round(median_ratio, 3),
        "target_ratio": args.target_ratio,
        "cascade_beats_target": bool(median_ratio >= args.target_ratio),
        "cascade_routing": snap,
        # the exact two-tier ledger (CascadeMetrics.conservation):
        # submitted == answered_student + escalated_teacher + failed
        # + depth, checked at this instant — the same conservation
        # discipline the stream fast path extends to three tiers
        "cascade_conservation": cascade.metrics.conservation(),
        "escalation_rate": snap["escalation_rate"],
        "recompiles_post_warmup": int(
            telemetry.compile_watch.recompiles.value),
    })
    telemetry.close()
    flush()
    print(strict_dumps({
        "median_round_ratio": report["median_round_ratio"],
        "cascade_beats_target": report["cascade_beats_target"],
        "escalation_rate": report["escalation_rate"],
        "ap_within_tolerance":
            report["quality"]["within_tolerance"],
        "cascade_conservation_exact":
            report["cascade_conservation"]["exact"],
        "recompiles_post_warmup": report["recompiles_post_warmup"]}))


if __name__ == "__main__":
    main()
