#!/usr/bin/env python
"""Import reference PyTorch checkpoints into the Flax IMHN.

Maps the reference ``PoseNet`` state_dict (models/posenet.py; checkpoints
saved as {'weights': state_dict, ...}, train.py:149-162) onto this
framework's parameter tree, so published weights (e.g. PoseNet_52_epoch.pth,
config/config.py:23) can seed evaluation without retraining.

Layout transforms: conv (O,I,kh,kw) → (kh,kw,I,O); linear (O,I) → (I,O);
BN weight/bias/running_mean/running_var → scale/bias + batch_stats mean/var.

Verified by forward-output parity between the torch reference network and the
converted Flax model (tests/test_torch_import.py).

    python tools/import_torch_checkpoint.py --pth PoseNet_52_epoch.pth \
        --out checkpoints/imported --config canonical
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _conv(w) -> np.ndarray:
    return np.asarray(w).transpose(2, 3, 1, 0)


def _linear(w) -> np.ndarray:
    return np.asarray(w).transpose(1, 0)


class _Mapper:
    def __init__(self, sd: Dict):
        self.sd = {k: np.asarray(v) for k, v in sd.items()}
        self.params: Dict[str, np.ndarray] = {}
        self.stats: Dict[str, np.ndarray] = {}
        self.used = set()

    def conv(self, tname: str, fpath: str, bias: bool = False):
        self.params[f"{fpath}/kernel"] = _conv(self.sd[f"{tname}.weight"])
        self.used.add(f"{tname}.weight")
        if bias:
            self.params[f"{fpath}/bias"] = self.sd[f"{tname}.bias"]
            self.used.add(f"{tname}.bias")

    def bn(self, tname: str, fpath: str):
        self.params[f"{fpath}/scale"] = self.sd[f"{tname}.weight"]
        self.params[f"{fpath}/bias"] = self.sd[f"{tname}.bias"]
        self.stats[f"{fpath}/mean"] = self.sd[f"{tname}.running_mean"]
        self.stats[f"{fpath}/var"] = self.sd[f"{tname}.running_var"]
        for suffix in ("weight", "bias", "running_mean", "running_var",
                       "num_batches_tracked"):
            self.used.add(f"{tname}.{suffix}")

    def conv_block(self, tname: str, fpath: str):
        """reference Conv/DilatedConv with bn=True → ConvBlock."""
        self.conv(f"{tname}.conv", f"{fpath}/Conv_0")
        self.bn(f"{tname}.bn", f"{fpath}/BatchNorm_0")

    def residual(self, tname: str, fpath: str):
        """reference Residual → our Residual (conv/bn interleaved)."""
        for i, (ci, bi) in enumerate([(0, 1), (3, 4), (6, 7)]):
            self.conv(f"{tname}.convBlock.{ci}", f"{fpath}/Conv_{i}")
            self.bn(f"{tname}.convBlock.{bi}", f"{fpath}/BatchNorm_{i}")
        if f"{tname}.skipConv.0.weight" in self.sd:
            self.conv(f"{tname}.skipConv.0", f"{fpath}/Conv_3")
            self.bn(f"{tname}.skipConv.1", f"{fpath}/BatchNorm_3")

    def se(self, tname: str, fpath: str):
        for ti, fi in ((0, 0), (2, 1)):
            self.params[f"{fpath}/Dense_{fi}/kernel"] = _linear(
                self.sd[f"{tname}.fc.{ti}.weight"])
            self.params[f"{fpath}/Dense_{fi}/bias"] = \
                self.sd[f"{tname}.fc.{ti}.bias"]
            self.used |= {f"{tname}.fc.{ti}.weight", f"{tname}.fc.{ti}.bias"}


def convert_posenet_state_dict(sd: Dict, nstack: int = 4, depth: int = 4
                               ) -> Tuple[Dict, Dict]:
    """Reference PoseNet state_dict → (params, batch_stats) nested dicts
    for ``models.PoseNet`` (the canonical IMHN)."""
    m = _Mapper(sd)
    nscale = depth + 1

    # Backbone (layers_transposed.py:158-194): conv1+bn1, res1, res2, dilation
    m.conv("pre.conv1", "Backbone_0/ConvBlock_0/Conv_0")
    m.bn("pre.bn1", "Backbone_0/ConvBlock_0/BatchNorm_0")
    m.residual("pre.res1", "Backbone_0/Residual_0")
    m.residual("pre.res2", "Backbone_0/Residual_1")
    for k in range(6):
        m.conv_block(f"pre.dilation.{k}", f"Backbone_0/ConvBlock_{k + 1}")

    # Hourglasses: our creation order is down-path (skip, down) per depth,
    # innermost, then up-path (low3 residual + refine conv) deepest-first
    for i in range(nstack):
        f = f"Hourglass_{i}"
        t = f"hourglass.{i}.hg"
        for d in range(depth):
            m.residual(f"{t}.{d}.0", f"{f}/Residual_{2 * d}")       # skip
            m.residual(f"{t}.{d}.1", f"{f}/Residual_{2 * d + 1}")   # down
        m.residual(f"{t}.{depth - 1}.4", f"{f}/Residual_{2 * depth}")
        for up, d in enumerate(reversed(range(depth))):
            m.residual(f"{t}.{d}.2", f"{f}/Residual_{2 * depth + 1 + up}")
            m.conv_block(f"{t}.{d}.3", f"{f}/ConvBlock_{up}")

    # Features heads: per scale 2 ConvBlocks + SE
    for i in range(nstack):
        for j in range(nscale):
            t = f"features.{i}.before_regress.{j}"
            m.conv_block(f"{t}.0", f"Features_{i}/ConvBlock_{2 * j}")
            m.conv_block(f"{t}.1", f"Features_{i}/ConvBlock_{2 * j + 1}")
            m.se(f"{t}.2", f"Features_{i}/SELayer_{j}")

    # outs + merges, created interleaved per stack/scale in _regress_and_merge
    n = 0
    for i in range(nstack):
        for j in range(nscale):
            m.conv(f"outs.{i}.{j}.conv", f"ConvBlock_{n}/Conv_0", bias=True)
            n += 1
            if i != nstack - 1:
                m.conv_block(f"merge_preds.{i}.{j}.conv", f"ConvBlock_{n}")
                n += 1
                m.conv_block(f"merge_features.{i}.{j}.conv",
                             f"ConvBlock_{n}")
                n += 1

    unused = set(m.sd) - m.used
    unused = {k for k in unused if not k.endswith("num_batches_tracked")}
    assert not unused, f"unmapped reference weights: {sorted(unused)[:8]}"

    from flax.traverse_util import unflatten_dict

    def nest(flat: Dict[str, np.ndarray]) -> Dict:
        return unflatten_dict(flat, sep="/")

    return nest(m.params), nest(m.stats)


def main():
    ap = argparse.ArgumentParser(
        description="import a reference .pth checkpoint")
    ap.add_argument("--pth", required=True)
    ap.add_argument("--out", required=True, help="orbax checkpoint dir")
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--unsafe-load", action="store_true",
                    help="allow full pickle deserialization for legacy "
                         "checkpoints that are not plain state dicts "
                         "(runs arbitrary code from the file — only for "
                         "trusted checkpoints)")
    args = ap.parse_args()

    import torch

    from improved_body_parts_tpu.config import get_config

    # weights_only=True keeps torch.load to tensor payloads; a downloaded
    # .pth is untrusted input and the full pickle machinery executes code.
    payload = torch.load(args.pth, map_location="cpu",
                         weights_only=not args.unsafe_load)
    sd = payload.get("weights", payload)
    # strip DistributedDataParallel prefixes and the reference's Network
    # wrapper prefix (posenet.*)
    sd = {k.replace("module.", "").replace("posenet.", ""): v
          for k, v in sd.items()}
    cfg = get_config(args.config)
    if cfg.model.variant not in ("imhn", "imhn_independent"):
        raise SystemExit(
            f"config '{args.config}' selects variant '{cfg.model.variant}'; "
            "the reference .pth layout maps onto the canonical IMHN only "
            "(variants imhn / imhn_independent)")
    params, stats = convert_posenet_state_dict(sd, cfg.model.nstack,
                                               cfg.model.hourglass_depth)

    import orbax.checkpoint as ocp

    ocp.PyTreeCheckpointer().save(
        os.path.abspath(args.out),
        {"params": params, "batch_stats": stats, "opt_state": None,
         "step": 0, "swa_params": None, "swa_count": None,
         "epoch": int(payload.get("epoch", 0)),
         "train_loss": float(payload.get("train_loss", 0.0)),
         "best_loss": float(payload.get("train_loss", 0.0))},
        force=True)
    print(f"imported {args.pth} -> {args.out}")


if __name__ == "__main__":
    main()
