#!/usr/bin/env python
"""End-to-end inference FPS: network forward + full decode to skeletons.

The reference's end-to-end rate is dominated by its pure-Python keypoint
assignment (5.2 FPS on a Xeon, reference: README.md:68); a 3rd-party C++
rebuild of the post-processing reached 7-8 FPS end-to-end single-scale+flip
(reference: README.md:121). This tool measures ours on the active platform,
three ways:

1. full ensemble path (``Predictor.predict`` -> host decode) — the
   evaluate.py-equivalent protocol, single scale + flip;
2. fast path (``predict_fast``: on-device NMS, scaled-res decode);
3. pipelined fast path (``pipelined_inference``: forward(N+1) overlaps
   threaded decode(N));
4. compact path (``predict_compact``: on-device top-K peak extraction +
   limb pair statistics; ~1 MB/image crosses the device boundary instead
   of full maps), sequential and pipelined.

Caveat: with randomly initialized weights the network's maps (and thus the
decode workload) do not reflect trained behavior — near-zero maps give the
decoder almost nothing to assemble. ``--planted N`` fixes that: the model's
output is augmented with ground-truth-style maps for N synthetic people
(the real forward still runs and contributes, so device time is honest),
giving the decode/assembly stages a trained-model-like workload. With an
imported reference checkpoint (tools/import_torch_checkpoint.py) this tool
measures the real thing.

    python tools/e2e_bench.py --images 30 --planted 3 --out E2E_BENCH.json
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


def synth_images(n, size, rng):
    """BGR uint8 images with mild structure (blobs + gradient)."""
    import numpy as np

    imgs = []
    for _ in range(n):
        img = rng.integers(0, 60, (size, size, 3)).astype(np.uint8)
        yy, xx = np.mgrid[0:size, 0:size]
        for _ in range(rng.integers(2, 5)):
            cx, cy = rng.integers(size // 8, 7 * size // 8, 2)
            r = rng.integers(size // 16, size // 6)
            blob = np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * r * r))
            img = np.clip(img + (blob[..., None] * 180), 0, 255
                          ).astype(np.uint8)
        imgs.append(img)
    return imgs


def planted_maps(skeleton, n_people, rng, canvas=1024):
    """Stride-grid GT maps for N synthetic stick people (the data
    pipeline's own Heatmapper), used to give the decode stage a
    trained-model-like workload.  ``canvas`` must cover the predictor's
    padded input size (boxsize-scaled, e.g. 640-odd for the default
    protocol); people are planted in the top-left boxsize-ish region so
    they land inside the valid area for typical bench sizes."""
    import dataclasses

    import numpy as np

    from improved_body_parts_tpu.data.heatmapper import Heatmapper

    sk = dataclasses.replace(skeleton, width=canvas, height=canvas)
    joints = np.zeros((n_people, sk.num_parts, 3), np.float32)
    joints[:, :, 2] = 2
    layout = [("nose", 0, 0.12), ("neck", 0, 0.21), ("Rsho", -0.09, 0.22),
              ("Lsho", 0.09, 0.22), ("Relb", -0.13, 0.33),
              ("Lelb", 0.13, 0.33), ("Rwri", -0.14, 0.43),
              ("Lwri", 0.14, 0.43), ("Rhip", -0.05, 0.45),
              ("Lhip", 0.05, 0.45), ("Rkne", -0.06, 0.59),
              ("Lkne", 0.06, 0.59), ("Rank", -0.06, 0.72),
              ("Lank", 0.06, 0.72), ("Reye", -0.02, 0.10),
              ("Leye", 0.02, 0.10), ("Rear", -0.04, 0.11),
              ("Lear", 0.04, 0.11)]
    region = canvas * 0.6  # keep people inside the typical valid area
    for p in range(n_people):
        cx = rng.uniform(0.2, 0.8) * region
        scale = rng.uniform(0.5, 0.8) * region
        y0 = rng.uniform(0.0, 0.2) * region
        for name, dx, dy in layout:
            joints[p, sk.parts_dict[name]] = [cx + dx * scale,
                                              y0 + dy * scale, 1]
    maps = Heatmapper(sk).create_heatmaps(
        joints, np.ones(sk.grid_shape, np.float32))
    return (maps + rng.uniform(0, 1e-6, maps.shape)).astype(np.float32)


class PlantedModel:
    """Wraps the real model: output = planted GT maps + 1e-3 × the real
    last-stack output — the full forward still runs (honest device time)
    while the maps contain decodable people.

    Flip-aware: the Predictor's lanes are [straight..., mirrored...] (first
    half straight in BOTH the 2-lane single and 2N-lane batch programs), so
    the mirror lanes get the width-flipped, channel-permuted maps — the
    flip-ensemble merge then reconstructs exactly the planted people at
    full amplitude (no ghosts, no halving)."""

    def __init__(self, model, maps, skeleton):
        self.model = model
        self.maps = maps  # (H/stride, W/stride, C) numpy
        self.skeleton = skeleton

    def apply(self, variables, imgs, train=False):
        import jax.numpy as jnp

        sk = self.skeleton
        preds = self.model.apply(variables, imgs, train=train)
        out = preds[-1][0]
        assert (self.maps.shape[0] >= out.shape[1]
                and self.maps.shape[1] >= out.shape[2]), (
            "planted canvas smaller than the model grid — raise canvas")
        m = jnp.asarray(self.maps[:out.shape[1], :out.shape[2]])
        # what a mirrored input would produce: L/R channel swap (the flip
        # orders are involutions) + width flip
        mm = jnp.concatenate(
            [m[..., :sk.paf_layers][..., jnp.asarray(sk.flip_paf_ord)],
             m[..., sk.heat_start:sk.num_layers]
             [..., jnp.asarray(sk.flip_heat_ord)]], axis=-1)[:, ::-1]
        n = out.shape[0] // 2
        planted = jnp.concatenate(
            [m[None] + 1e-3 * out[:n], mm[None] + 1e-3 * out[n:]], axis=0)
        return [[planted]]


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="canonical")
    ap.add_argument("--size", type=int, default=512)
    ap.add_argument("--images", type=int, default=30)
    ap.add_argument("--decode-workers", type=int, default=2)
    ap.add_argument("--out", default="E2E_BENCH.json")
    ap.add_argument("--modes", default="full,fast,pipelined,compact,"
                    "compact-pipelined,compact-batch,device-decode,"
                    "device-decode-batch",
                    help="comma-separated subset of sections to run")
    ap.add_argument("--device-decode", action="store_true",
                    help="run ONLY the fused device-decode sections "
                         "(forward + peak extraction + greedy assembly "
                         "in one XLA program; PR 9's serve lane), "
                         "sequential and batched-pipelined")
    ap.add_argument("--batch", type=int, default=8,
                    help="chunk size for the compact-batch throughput mode")
    ap.add_argument("--params-dtype", default="auto",
                    choices=["auto", "bf16", "fp32"],
                    help="weight storage; auto = bf16 on TPU, fp32 elsewhere")
    ap.add_argument("--planted", type=int, default=0,
                    help="plant GT-style maps for N synthetic people into "
                         "the model output (realistic decode workload)")
    args = ap.parse_args()
    modes = (({"device-decode", "device-decode-batch"}
              if args.device_decode else set(args.modes.split(","))))

    from improved_body_parts_tpu.utils import (
        apply_platform_env, devices_with_timeout)
    apply_platform_env()

    import jax
    import numpy as np

    devices = devices_with_timeout(900)
    platform = devices[0].platform
    print(f"platform={platform}", flush=True)

    from improved_body_parts_tpu.config import get_config
    from improved_body_parts_tpu.infer.decode import decode
    from improved_body_parts_tpu.infer.pipeline import pipelined_inference
    from improved_body_parts_tpu.infer.predict import Predictor
    from improved_body_parts_tpu.models import build_model

    cfg = get_config(args.config)
    model = build_model(cfg)
    rng = np.random.default_rng(0)
    imgs = synth_images(args.images, args.size, rng)

    import jax.numpy as jnp

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, args.size, args.size, 3)),
                           train=False)
    from improved_body_parts_tpu.utils.precision import resolve_params_dtype

    # bf16 weight storage on TPU by default (PERF_AUDIT_BF16.json win;
    # reduced-precision eval matches the reference's AMP-O1, evaluate.py:636)
    variables = resolve_params_dtype(args.params_dtype, variables)
    if args.planted > 0:
        model = PlantedModel(model, planted_maps(cfg.skeleton, args.planted,
                                                 rng), cfg.skeleton)
        report_planted = args.planted
    else:
        report_planted = 0
    pred = Predictor(model, variables, cfg.skeleton)

    report = {"platform": platform, "config": args.config,
              "size": args.size, "images": args.images,
              "planted_people": report_planted,
              "reference_fps": {"python_assignment": 5.2,
                                "cpp_rebuild_e2e": "7-8"}}

    def flush():
        with open(args.out, "w") as f:
            strict_dump(report, f, indent=2)

    # --- 1. full ensemble (single scale + flip) + host decode -----------
    if "full" in modes:
        run_full(pred, imgs, decode, cfg, report, flush)
    if "fast" in modes:
        run_fast(pred, imgs, decode, cfg, report, flush)
    if "pipelined" in modes:
        run_pipelined(pred, imgs, pipelined_inference, args, report, flush)
    if modes & {"compact", "compact-pipelined", "compact-batch"}:
        run_compact_modes(pred, imgs, decode, cfg, args, report, flush,
                          modes, pipelined_inference)
    if modes & {"device-decode", "device-decode-batch"}:
        run_device_decode_modes(pred, imgs, cfg, args, report, flush,
                                modes, pipelined_inference)
    print(strict_dumps(report))


def run_full(pred, imgs, decode, cfg, report, flush):
    heat, paf = pred.predict(imgs[0])  # compile
    n_dec = 0
    t0 = time.perf_counter()
    for im in imgs:
        heat, paf = pred.predict(im)
        people = decode(heat, paf, pred.params, cfg.skeleton)
        n_dec += len(people)
    dt = (time.perf_counter() - t0) / len(imgs)
    report["full_path_fps"] = round(1.0 / dt, 2)
    report["full_path_ms"] = round(dt * 1e3, 1)
    flush()
    print(f"full ensemble+decode: {1.0 / dt:.2f} FPS "
          f"({dt * 1e3:.0f} ms/img, {n_dec} detections)", flush=True)


def run_fast(pred, imgs, decode, cfg, report, flush):
    pred.predict_fast(imgs[0])  # compile
    t0 = time.perf_counter()
    for im in imgs:
        heat, paf, mask, scale = pred.predict_fast(im)
        decode(heat, paf, pred.params, cfg.skeleton, peak_mask=mask,
               coord_scale=scale)
    dt = (time.perf_counter() - t0) / len(imgs)
    report["fast_path_fps"] = round(1.0 / dt, 2)
    flush()
    print(f"fast path: {1.0 / dt:.2f} FPS", flush=True)


def run_pipelined(pred, imgs, pipelined_inference, args, report, flush):
    t0 = time.perf_counter()
    n = sum(1 for _ in pipelined_inference(
        pred, imgs, decode_workers=args.decode_workers))
    dt = (time.perf_counter() - t0) / n
    report["pipelined_fps"] = round(1.0 / dt, 2)
    report["decode_workers"] = args.decode_workers
    flush()
    print(f"pipelined: {1.0 / dt:.2f} FPS", flush=True)


def run_compact_modes(pred, imgs, decode, cfg, args, report, flush, modes,
                      pipelined_inference):
    from improved_body_parts_tpu.infer.decode import (
        CompactOverflow, decode_compact)

    def run_compact(im):
        # same transparent fallback as pipelined_inference / process_image
        try:
            decode_compact(pred.predict_compact(im), pred.params,
                           cfg.skeleton)
        except CompactOverflow:
            heat, paf, mask, scale = pred.predict_fast(im)
            decode(heat, paf, pred.params, cfg.skeleton, peak_mask=mask,
                   coord_scale=scale)

    if modes & {"compact", "compact-pipelined"}:
        run_compact(imgs[0])  # compile (batch mode compiles its own program)
    if "compact" in modes:
        t0 = time.perf_counter()
        for im in imgs:
            run_compact(im)
        dt = (time.perf_counter() - t0) / len(imgs)
        report["compact_fps"] = round(1.0 / dt, 2)
        flush()
        print(f"compact: {1.0 / dt:.2f} FPS", flush=True)

    if "compact-pipelined" in modes:
        t0 = time.perf_counter()
        n = sum(1 for _ in pipelined_inference(
            pred, imgs, decode_workers=args.decode_workers, compact=True))
        dt = (time.perf_counter() - t0) / n
        report["compact_pipelined_fps"] = round(1.0 / dt, 2)
        report["decode_workers"] = args.decode_workers
        flush()
        print(f"compact pipelined: {1.0 / dt:.2f} FPS", flush=True)

    if "compact-batch" in modes:
        # throughput mode: N images + mirrors per dispatch, pipelined
        b = args.batch
        list(pipelined_inference(            # compile the batched program
            pred, imgs[:b], decode_workers=args.decode_workers,
            compact_batch=b))
        t0 = time.perf_counter()
        n = sum(1 for _ in pipelined_inference(
            pred, imgs, decode_workers=args.decode_workers,
            compact_batch=b))
        dt = (time.perf_counter() - t0) / n
        report["compact_batch_fps"] = round(1.0 / dt, 2)
        report["compact_batch"] = b
        flush()
        print(f"compact batch({b}) pipelined: {1.0 / dt:.2f} FPS",
              flush=True)


def run_device_decode_modes(pred, imgs, cfg, args, report, flush, modes,
                            pipelined_inference):
    """The FUSED lane (PR 9): forward + compact extraction + greedy
    assembly in ONE device program; the host finishes with an O(people)
    id→coordinate lookup, falling back one level per overflow class
    (``infer.pipeline.device_decode_fn``)."""
    from improved_body_parts_tpu.infer.pipeline import device_decode_fn

    finish = device_decode_fn(pred, pred.params, cfg.skeleton)

    if "device-decode" in modes:
        finish(pred.predict_decoded(imgs[0]), imgs[0])   # compile
        fused = 0
        t0 = time.perf_counter()
        for im in imgs:
            res = pred.predict_decoded(im)
            fused += bool(res.ok)
            finish(res, im)
        dt = (time.perf_counter() - t0) / len(imgs)
        report["device_decode_fps"] = round(1.0 / dt, 2)
        report["device_decode_fused"] = fused
        report["device_decode_host_fallback"] = len(imgs) - fused
        flush()
        print(f"device-decode: {1.0 / dt:.2f} FPS "
              f"({fused}/{len(imgs)} fused)", flush=True)

    if "device-decode-batch" in modes:
        b = args.batch
        list(pipelined_inference(            # compile the batch programs
            pred, imgs[:b], decode_workers=args.decode_workers,
            compact_batch=b, device_decode=True))
        t0 = time.perf_counter()
        n = sum(1 for _ in pipelined_inference(
            pred, imgs, decode_workers=args.decode_workers,
            compact_batch=b, device_decode=True))
        dt = (time.perf_counter() - t0) / n
        report["device_decode_batch_fps"] = round(1.0 / dt, 2)
        report["device_decode_batch"] = b
        flush()
        print(f"device-decode batch({b}) pipelined: {1.0 / dt:.2f} FPS",
              flush=True)


if __name__ == "__main__":
    main()
