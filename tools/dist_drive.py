#!/usr/bin/env python
"""Multi-process distributed training drive at the flagship shape.

Runs the REAL multi-process path — ``tools/train.py --coordinator
--num-processes 2`` (jax.distributed over Gloo on CPU; the same code path
brings up TPU pods over DCN) — on the synth_deep production-architecture
config, exercises a CROSS-PROCESS checkpoint/resume boundary, and pins
per-epoch loss parity against a single-process run on the same data
(reference: train_distributed.py:69-84 NCCL bring-up; :149-197 resume;
parity is how the reference validated its DDP path).

Why parity is exact up to float tolerance: the host shard is strided
(data/dataset.py ``host_shard``: process p takes perm[p::P]), so step k's
GLOBAL batch in a P-process run is the same SAMPLE SET as step k of a
single-process run over a P-device mesh, and augmentation is
(seed, epoch, index)-keyed — order within the batch differs, but the
mean loss and batch-wide BN statistics are order-invariant.

    python tools/dist_drive.py --out DIST_DRIVE.json
"""
import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_train(h5, val_h5, ckpt_dir, epochs, env_extra, extra_args=(),
              timeout=3600, log_path=None, config="synth_deep"):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu"})
    env.update(env_extra)
    args = [sys.executable, os.path.join(REPO, "tools", "train.py"),
            "--config", config, "--train-h5", h5, "--val-h5", val_h5,
            "--checkpoint-dir", ckpt_dir, "--epochs", str(epochs),
            "--workers", "0", "--print-freq", "1"] + list(extra_args)
    proc = subprocess.run(args, capture_output=True, text=True, env=env,
                          timeout=timeout)
    if log_path:
        with open(log_path, "w") as f:
            f.write(proc.stdout + "\n--- stderr ---\n" + proc.stderr)
    if proc.returncode != 0:
        raise RuntimeError(f"train rc={proc.returncode}\n"
                           f"{proc.stdout[-1500:]}\n{proc.stderr[-1500:]}")
    return proc


def epoch_losses(ckpt_dir):
    """Epoch → loss from the append-only log, LAST occurrence winning —
    a retried/relaunched run may append a duplicate epoch line."""
    with open(os.path.join(ckpt_dir, "log")) as f:
        entries = re.findall(r"Epoch (\d+)\ttrain_loss: ([0-9.eE+-]+)",
                             f.read())
    by_epoch = {int(e): float(v) for e, v in entries}
    return [by_epoch[e] for e in sorted(by_epoch)]


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="synth_deep",
                    help="synth_deep = the flagship-shape drive; tiny for "
                         "a fast protocol smoke")
    ap.add_argument("--images", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3,
                    help="total epochs; the 2-process run restarts from a "
                         "checkpoint after epoch --resume-after")
    ap.add_argument("--resume-after", type=int, default=2)
    ap.add_argument("--port", type=int, default=12897)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default="DIST_DRIVE.json")
    ap.add_argument("--tolerance", type=float, default=0.02,
                    help="max relative per-epoch loss difference")
    args = ap.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from improved_body_parts_tpu.data import build_fixture

    work = os.path.abspath(args.workdir
                           or tempfile.mkdtemp(prefix="dist_drive_"))
    os.makedirs(work, exist_ok=True)
    h5 = os.path.join(work, "corpus.h5")
    n_rec = build_fixture(h5, num_images=args.images, people_per_image=2,
                          img_size=(384, 512), image_size=256, seed=0,
                          drawn=True)
    # a val corpus too: per-epoch eval is a COLLECTIVE in multi-process
    # runs (every host must enter it), so the drive exercises that path
    val_h5 = os.path.join(work, "val_corpus.h5")
    build_fixture(val_h5, num_images=max(args.images // 4, 2),
                  people_per_image=2, img_size=(384, 512), image_size=256,
                  seed=99, drawn=True)
    print(f"corpus: {n_rec} records", flush=True)

    # --- phase A: single process, 2-device mesh (the parity arm) --------
    ckpt_a = os.path.join(work, "ckpt_single")
    t0 = time.time()
    run_train(h5, val_h5, ckpt_a, args.epochs,
              {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
              log_path=os.path.join(work, "single.log"),
              config=args.config)
    t_single = time.time() - t0
    losses_a = epoch_losses(ckpt_a)
    print(f"single-process losses: {losses_a} ({t_single:.0f}s)", flush=True)

    # --- phase B: 2 processes, 1 device each, with a cross-process
    # checkpoint/resume boundary after --resume-after epochs -------------
    ckpt_b = os.path.join(work, "ckpt_dist")
    coord = f"127.0.0.1:{args.port}"
    env1 = {"XLA_FLAGS": "--xla_force_host_platform_device_count=1"}

    def _latest_epoch():
        import glob as g
        eps = []
        for p in g.glob(os.path.join(ckpt_b, "epoch_*")):
            m = re.search(r"epoch_(\d+)$", p)
            if m:
                eps.append(int(m.group(1)))
        return max(eps) if eps else -1

    def launch_pair(end_epoch, resume, attempt=0):
        if resume:
            # --epochs is ADDITIONAL after a resume (fit runs
            # range(start_epoch, start_epoch + epochs)); compute the
            # remainder from the latest checkpoint so a retry after a
            # partial run stays idempotent
            additional = end_epoch - (_latest_epoch() + 1)
            if additional <= 0:
                return
        else:
            additional = end_epoch
        procs = []
        for pid in (0, 1):
            env = dict(os.environ)
            env.update({"JAX_PLATFORMS": "cpu"})
            env.update(env1)
            extra = ["--coordinator", coord, "--num-processes", "2",
                     "--process-id", str(pid)]
            if resume:
                extra += ["--resume", "auto"]
            cmd = [sys.executable, os.path.join(REPO, "tools", "train.py"),
                   "--config", args.config, "--train-h5", h5,
                   "--val-h5", val_h5,
                   "--checkpoint-dir", ckpt_b, "--epochs", str(additional),
                   "--workers", "0", "--print-freq", "1"] + extra
            log = open(os.path.join(work, f"dist_rank{pid}"
                       f"{'_resumed' if resume else ''}.log"), "w")
            procs.append((subprocess.Popen(cmd, stdout=log, stderr=log,
                                           env=env), log))
        rcs = []
        try:
            for p, log in procs:
                rcs.append(p.wait(timeout=3600))
        except subprocess.TimeoutExpired:
            # a wedged rank must not orphan its peer: both keep the
            # coordinator port bound and poison the retry
            for p, _ in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            rcs = [p.returncode for p, _ in procs]
        finally:
            for _, log in procs:
                log.close()
        if any(rc != 0 for rc in rcs) and attempt == 0:
            # Gloo's context bring-up has a fixed ~30 s window; on a
            # contended host the ranks can drift past it (compiles are
            # per-process).  One retry with a warm compile cache keeps
            # the ranks aligned.
            print(f"rank failure rcs={rcs}; retrying once with a warm "
                  "cache", flush=True)
            return launch_pair(end_epoch, resume, attempt=1)
        assert all(rc == 0 for rc in rcs), (
            f"distributed ranks failed rcs={rcs}; see {work}/dist_rank*.log")

    t0 = time.time()
    launch_pair(args.resume_after, resume=False)
    print(f"2-process epochs 0..{args.resume_after - 1} done", flush=True)
    # the resume boundary: a fresh pair of processes picks up the
    # checkpoint both ranks agreed on
    launch_pair(args.epochs, resume=True)
    t_dist = time.time() - t0
    losses_b = epoch_losses(ckpt_b)
    print(f"2-process losses:      {losses_b} ({t_dist:.0f}s)", flush=True)

    assert len(losses_a) == len(losses_b) == args.epochs, (
        losses_a, losses_b)
    rel = [abs(a - b) / max(abs(a), 1e-9)
           for a, b in zip(losses_a, losses_b)]
    parity_ok = max(rel) <= args.tolerance
    result = {
        "config": args.config,
        "records": n_rec,
        "epochs": args.epochs,
        "resume_boundary_after_epoch": args.resume_after,
        "single_process_losses": losses_a,
        "two_process_losses": losses_b,
        "relative_diff_per_epoch": [round(r, 5) for r in rel],
        "tolerance": args.tolerance,
        "parity_ok": bool(parity_ok),
        "seconds": {"single": round(t_single, 1),
                    "two_process": round(t_dist, 1)},
        "protocol": "phase A: 1 process x 2 virtual CPU devices; phase B: "
                    "2 processes x 1 device over jax.distributed (Gloo), "
                    "restarted from the shared checkpoint after epoch "
                    f"{args.resume_after}; strided host shards make each "
                    "step's global batch the same sample set in both "
                    "phases (see module docstring)",
        "per_process_logs": sorted(
            os.path.basename(p) for p in os.listdir(work)
            if p.endswith(".log")),
        "workdir": work,
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    if not parity_ok:
        raise SystemExit(f"loss parity exceeded tolerance: {rel}")


if __name__ == "__main__":
    main()
