#!/usr/bin/env python
"""Multi-process distributed training drive at the flagship shape.

Runs the REAL multi-process path — ``tools/train.py --coordinator
--num-processes 2`` (jax.distributed over Gloo on CPU; the same code path
brings up TPU pods over DCN) — on the synth_deep production-architecture
config, in THREE arms (reference: train_distributed.py:69-84 NCCL
bring-up; :149-197 resume; parity is how the reference validated DDP):

A. single process × 2 virtual devices (the topology-parity arm);
B. 2 processes × 1 device, straight through all epochs;
C. 2 processes × 1 device with a CROSS-PROCESS checkpoint/resume
   boundary after ``--resume-after`` epochs;
P. the GSPMD-PARTITIONED step (``--partition --mesh-model 2``) on an
   8-virtual-device ('data': 4, 'model': 2) mesh — mesh shape and
   realized state-sharding counts recorded into DIST_DRIVE.json, so
   the artifact proves the partitioned program trains, not a dryrun.
   ``--refresh-multichip`` additionally reruns the multichip entry
   (now partitioned) and rewrites the MULTICHIP_r0*.json artifacts.

Two distinct parity claims, separately asserted:

- RESUME parity (C vs B): must be BIT-EXACT (rel diff ≤ 1e-6 per
  epoch).  Restarting both ranks from the shared checkpoint restores
  params / optimizer momentum / schedule step / RNG-keyed data order
  completely, so the resumed trajectory is indistinguishable from the
  uninterrupted one.  Round 5 measured exactly this (44.12479782104492
  at the post-boundary epoch in both arms).
- TOPOLOGY parity (B vs A): same per-step global SAMPLE SET (strided
  host shards: process p takes perm[p::P]; augmentation is
  (seed, epoch, index)-keyed) but a different order of floating-point
  reduction — so the first epoch must agree to ``--tolerance`` (~0.1%
  measured), while later epochs drift chaotically as tiny weight
  differences amplify through a steep loss descent (round 5 measured
  0.09% / 0.16% / 7.2% over three epochs; the 7.2% is trajectory
  divergence, NOT a state bug — arm C reproduces arm B bit-exactly).
  Only the FIRST epoch is asserted; the full per-epoch drift is
  reported for the record.

    python tools/dist_drive.py --out DIST_DRIVE.json
"""
import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from improved_body_parts_tpu.obs.events import (  # noqa: E402
    strict_dump,
    strict_dumps,
)


def run_train(h5, val_h5, ckpt_dir, epochs, env_extra, extra_args=(),
              timeout=3600, log_path=None, config="synth_deep"):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu"})
    env.update(env_extra)
    args = [sys.executable, os.path.join(REPO, "tools", "train.py"),
            "--config", config, "--train-h5", h5, "--val-h5", val_h5,
            "--checkpoint-dir", ckpt_dir, "--epochs", str(epochs),
            "--workers", "0", "--print-freq", "1"] + list(extra_args)
    proc = subprocess.run(args, capture_output=True, text=True, env=env,
                          timeout=timeout)
    if log_path:
        with open(log_path, "w") as f:
            f.write(proc.stdout + "\n--- stderr ---\n" + proc.stderr)
    if proc.returncode != 0:
        raise RuntimeError(f"train rc={proc.returncode}\n"
                           f"{proc.stdout[-1500:]}\n{proc.stderr[-1500:]}")
    return proc


def epoch_losses(ckpt_dir):
    """Epoch → loss from the append-only log, LAST occurrence winning —
    a retried/relaunched run may append a duplicate epoch line."""
    log = os.path.join(ckpt_dir, "log")
    if not os.path.exists(log):
        return []
    with open(log) as f:
        entries = re.findall(r"Epoch (\d+)\ttrain_loss: ([0-9.eE+-]+)",
                             f.read())
    by_epoch = {int(e): float(v) for e, v in entries}
    return [by_epoch[e] for e in sorted(by_epoch)]


def have_epochs(ckpt_dir, n):
    """True when the arm already trained ≥ n epochs (idempotent reruns:
    a completed arm is parsed, not retrained)."""
    return len(epoch_losses(ckpt_dir)) >= n


def run_partitioned_arm(work, args):
    """Arm P: the GSPMD-PARTITIONED step on an 8-virtual-device mesh
    (tools/train.py --partition: state sharded per the IMHN rules over
    'model', batch over 'data', contiguous-slab input shard).  Its own
    tiny-config corpus — the arm proves the partitioned PROGRAM trains
    end-to-end and records the realized layout; loss-parity against
    the replicated arms is pinned in tests/test_partition.py."""
    from improved_body_parts_tpu.data import build_fixture

    if args.partition_epochs <= 0:
        return None
    p_h5 = os.path.join(work, "partition_corpus.h5")
    if not os.path.exists(p_h5):
        build_fixture(p_h5, num_images=8, people_per_image=2,
                      img_size=(384, 512), image_size=128, seed=0,
                      drawn=True)
    ckpt_p = os.path.join(work, "ckpt_partitioned")
    t0 = time.time()
    ran_part = not have_epochs(ckpt_p, args.partition_epochs)
    if ran_part:
        run_train(p_h5, "", ckpt_p, args.partition_epochs,
                  {"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=8"},
                  extra_args=["--partition", "--mesh-model", "2"],
                  log_path=os.path.join(work, "partitioned.log"),
                  config="tiny")
    t_part = time.time() - t0
    losses_p = epoch_losses(ckpt_p)[:args.partition_epochs]
    mesh_shape = sharding = None
    try:
        with open(os.path.join(work, "partitioned.log")) as f:
            log_text = f.read()
        m = re.search(r"mesh=data:(\d+),model:(\d+)", log_text)
        if m:
            mesh_shape = {"data": int(m.group(1)),
                          "model": int(m.group(2))}
        m = re.search(r"partitioned state: \{'sharded': (\d+), "
                      r"'replicated': (\d+)\}", log_text)
        if m:
            sharding = {"sharded": int(m.group(1)),
                        "replicated": int(m.group(2))}
    except OSError:
        pass
    partitioned = {
        "config": "tiny",
        "epochs": args.partition_epochs,
        "losses": losses_p,
        "mesh": mesh_shape,
        "realized_state_sharding": sharding,
        "finite": all(l == l and abs(l) != float("inf")
                      for l in losses_p),
        "ran": ran_part,
        "seconds": round(t_part, 1) if ran_part else None,
        "protocol": "tools/train.py --partition --mesh-model 2 on 8 "
                    "virtual CPU devices (tiny config, own fixture "
                    "corpus); mesh + realized sharding parsed from the "
                    "run's own log",
    }
    print(f"P partitioned (8 virtual devices): losses={losses_p} "
          f"mesh={mesh_shape} sharding={sharding}", flush=True)
    assert len(losses_p) == args.partition_epochs, losses_p
    assert partitioned["finite"], losses_p
    assert sharding and sharding["sharded"] > 0, (
        "partitioned arm realized no sharded state leaves", sharding)
    return partitioned


def refresh_multichip(paths):
    """Rerun the multichip entry (__graft_entry__.py dryrun 8 — the
    GSPMD-partitioned step since ISSUE 12) ONCE PER artifact file, so
    the r0N round files each record a genuinely executed run (the entry
    is seed-deterministic, so the tails agree — but a flake would
    surface in its own round instead of being copied over)."""
    import glob as g

    paths = paths or sorted(
        g.glob(os.path.join(REPO, "MULTICHIP_r0*.json")))
    for i, path in enumerate(paths):
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "__graft_entry__.py"),
             "dryrun", "8"],
            capture_output=True, text=True, timeout=1200,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        tail = (proc.stdout.strip().splitlines() or [""])[-1] + "\n"
        refresh = {"n_devices": 8, "rc": proc.returncode,
                   "ok": proc.returncode == 0, "skipped": False,
                   "tail": tail,
                   "refresh_run": i + 1,
                   "seconds": round(time.time() - t0, 1)}
        assert proc.returncode == 0, proc.stderr[-1500:]
        assert "partitioned_multichip" in tail, tail
        with open(path, "w") as f:
            strict_dump(refresh, f, indent=1)
        print(f"refreshed {path} (run {i + 1}): {tail.strip()}")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="synth_deep",
                    help="synth_deep = the flagship-shape drive; tiny for "
                         "a fast protocol smoke")
    ap.add_argument("--images", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3,
                    help="total epochs; the 2-process run restarts from a "
                         "checkpoint after epoch --resume-after")
    ap.add_argument("--resume-after", type=int, default=2)
    ap.add_argument("--port", type=int, default=12897)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out", default="DIST_DRIVE.json")
    ap.add_argument("--tolerance", type=float, default=0.02,
                    help="max relative per-epoch loss difference")
    ap.add_argument("--partition-epochs", type=int, default=2,
                    help="epochs for the partitioned arm (0 skips it)")
    ap.add_argument("--partition-only", action="store_true",
                    help="run ONLY arm P (+ --refresh-multichip when "
                         "given) and MERGE its record into an existing "
                         "--out artifact — the A/B/C parity arms at "
                         "flagship shape take hours and are "
                         "skip-resumable only in their original "
                         "workdir")
    ap.add_argument("--refresh-multichip", nargs="*", default=None,
                    metavar="PATH",
                    help="additionally run the partitioned multichip "
                         "entry (python __graft_entry__.py dryrun 8 — "
                         "the GSPMD-partitioned step since ISSUE 12) "
                         "and rewrite these artifact files with its "
                         "result (no paths = MULTICHIP_r0*.json in the "
                         "repo root)")
    args = ap.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from improved_body_parts_tpu.data import build_fixture

    work = os.path.abspath(args.workdir
                           or tempfile.mkdtemp(prefix="dist_drive_"))
    os.makedirs(work, exist_ok=True)

    if args.partition_only:
        partitioned = run_partitioned_arm(work, args)
        if partitioned is not None:
            # merge only a REAL record: --partition-epochs 0 (e.g. a
            # refresh-multichip-only invocation) must not clobber an
            # existing arm-P result with null
            result = {}
            if os.path.exists(args.out):
                with open(args.out) as f:
                    result = json.load(f)
            result["partitioned"] = partitioned
            with open(args.out, "w") as f:
                strict_dump(result, f, indent=2)
            print(strict_dumps({"partitioned": partitioned}))
        if args.refresh_multichip is not None:
            refresh_multichip(args.refresh_multichip)
        return

    h5 = os.path.join(work, "corpus.h5")
    val_h5 = os.path.join(work, "val_corpus.h5")
    # arms skip-resume on their logs, so the corpus they trained on must
    # not silently change under a rerun with different parameters —
    # pin the fixture params in the workdir and refuse a mismatch
    fixture_params = {"config": args.config, "images": args.images,
                      "epochs": args.epochs,
                      "resume_after": args.resume_after}
    params_path = os.path.join(work, "fixture_params.json")
    if os.path.exists(params_path):
        with open(params_path) as f:
            pinned = json.load(f)
        assert pinned == fixture_params, (
            f"workdir {work} was built with {pinned}, rerun requests "
            f"{fixture_params}; use a fresh --workdir")
        import h5py
        with h5py.File(h5, "r") as f:
            n_rec = len(f["dataset"])
    else:
        # a workdir with arm logs but no params file predates the pinning
        # (or crashed before the pin was written): rebuilding the corpus
        # under skip-resumed arms would compare losses across corpora
        stale = [d for d in ("ckpt_single", "ckpt_dist_straight",
                             "ckpt_dist")
                 if os.path.exists(os.path.join(work, d, "log"))]
        assert not stale, (
            f"workdir {work} has arm logs {stale} but no "
            "fixture_params.json; use a fresh --workdir")
        n_rec = build_fixture(h5, num_images=args.images,
                              people_per_image=2, img_size=(384, 512),
                              image_size=256, seed=0, drawn=True)
        # a val corpus too: per-epoch eval is a COLLECTIVE in
        # multi-process runs (every host must enter it), so the drive
        # exercises that path
        build_fixture(val_h5, num_images=max(args.images // 4, 2),
                      people_per_image=2, img_size=(384, 512),
                      image_size=256, seed=99, drawn=True)
        with open(params_path, "w") as f:
            strict_dump(fixture_params, f)
    print(f"corpus: {n_rec} records", flush=True)

    # --- arm A: single process, 2-device mesh (topology-parity arm) -----
    ckpt_a = os.path.join(work, "ckpt_single")
    t0 = time.time()
    ran_single = not have_epochs(ckpt_a, args.epochs)
    if ran_single:
        run_train(h5, val_h5, ckpt_a, args.epochs,
                  {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
                  log_path=os.path.join(work, "single.log"),
                  config=args.config)
    t_single = time.time() - t0
    losses_a = epoch_losses(ckpt_a)[:args.epochs]
    print(f"A single-process losses:    {losses_a} ({t_single:.0f}s)",
          flush=True)

    coord = f"127.0.0.1:{args.port}"
    env1 = {"XLA_FLAGS": "--xla_force_host_platform_device_count=1"}

    def _latest_epoch(ckpt_dir):
        import glob as g
        eps = []
        for p in g.glob(os.path.join(ckpt_dir, "epoch_*")):
            m = re.search(r"epoch_(\d+)$", p)
            if m:
                eps.append(int(m.group(1)))
        return max(eps) if eps else -1

    def launch_pair(ckpt_dir, tag, end_epoch, resume, attempt=0):
        if resume:
            # --epochs is ADDITIONAL after a resume (fit runs
            # range(start_epoch, start_epoch + epochs)); compute the
            # remainder from the latest checkpoint so a retry after a
            # partial run stays idempotent
            additional = end_epoch - (_latest_epoch(ckpt_dir) + 1)
            if additional <= 0:
                return
        else:
            additional = end_epoch
        procs = []
        for pid in (0, 1):
            env = dict(os.environ)
            env.update({"JAX_PLATFORMS": "cpu"})
            env.update(env1)
            extra = ["--coordinator", coord, "--num-processes", "2",
                     "--process-id", str(pid)]
            if resume:
                extra += ["--resume", "auto"]
            cmd = [sys.executable, os.path.join(REPO, "tools", "train.py"),
                   "--config", args.config, "--train-h5", h5,
                   "--val-h5", val_h5,
                   "--checkpoint-dir", ckpt_dir,
                   "--epochs", str(additional),
                   "--workers", "0", "--print-freq", "1"] + extra
            log = open(os.path.join(work, f"dist_rank{pid}{tag}.log"), "w")
            procs.append((subprocess.Popen(cmd, stdout=log, stderr=log,
                                           env=env), log))
        rcs = []
        try:
            for p, log in procs:
                rcs.append(p.wait(timeout=3600))
        except subprocess.TimeoutExpired:
            # a wedged rank must not orphan its peer: both keep the
            # coordinator port bound and poison the retry
            for p, _ in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            rcs = [p.returncode for p, _ in procs]
        finally:
            for _, log in procs:
                log.close()
        if any(rc != 0 for rc in rcs) and attempt == 0:
            # Gloo's context bring-up has a fixed ~30 s window; on a
            # contended host the ranks can drift past it (compiles are
            # per-process).  One retry with a warm compile cache keeps
            # the ranks aligned.
            print(f"rank failure rcs={rcs}; retrying once with a warm "
                  "cache", flush=True)
            return launch_pair(ckpt_dir, tag, end_epoch, resume, attempt=1)
        assert all(rc == 0 for rc in rcs), (
            f"distributed ranks failed rcs={rcs}; see {work}/dist_rank*.log")

    # --- arm B: 2 processes, straight through (no boundary) -------------
    ckpt_b = os.path.join(work, "ckpt_dist_straight")
    t0 = time.time()
    ran_straight = not have_epochs(ckpt_b, args.epochs)
    if ran_straight:
        launch_pair(ckpt_b, "_straight", args.epochs, resume=False)
    t_straight = time.time() - t0
    losses_b = epoch_losses(ckpt_b)[:args.epochs]
    print(f"B 2-process straight:       {losses_b} ({t_straight:.0f}s)",
          flush=True)

    # --- arm C: 2 processes with a cross-process resume boundary --------
    ckpt_c = os.path.join(work, "ckpt_dist")
    t0 = time.time()
    ran_dist = not have_epochs(ckpt_c, args.epochs)
    if ran_dist:
        if not have_epochs(ckpt_c, args.resume_after):
            launch_pair(ckpt_c, "", args.resume_after, resume=False)
        print(f"C 2-process epochs 0..{args.resume_after - 1} done",
              flush=True)
        # the resume boundary: a fresh pair of processes picks up the
        # checkpoint both ranks agreed on
        launch_pair(ckpt_c, "_resumed", args.epochs, resume=True)
    t_dist = time.time() - t0
    losses_c = epoch_losses(ckpt_c)[:args.epochs]
    print(f"C 2-process with resume:    {losses_c} ({t_dist:.0f}s)",
          flush=True)

    partitioned = run_partitioned_arm(work, args)

    assert len(losses_a) == len(losses_b) == len(losses_c) == args.epochs, (
        losses_a, losses_b, losses_c)
    resume_rel = [abs(b - c) / max(abs(b), 1e-9)
                  for b, c in zip(losses_b, losses_c)]
    topology_rel = [abs(a - b) / max(abs(a), 1e-9)
                    for a, b in zip(losses_a, losses_b)]
    # resume must be EXACT; topology only bounded on the first epoch
    # (later epochs drift chaotically — see module docstring)
    resume_exact = max(resume_rel) <= 1e-6
    topology_ok = topology_rel[0] <= args.tolerance
    parity_ok = resume_exact and topology_ok
    result = {
        "config": args.config,
        "records": n_rec,
        "epochs": args.epochs,
        "resume_boundary_after_epoch": args.resume_after,
        "single_process_losses": losses_a,
        "two_process_straight_losses": losses_b,
        "two_process_resumed_losses": losses_c,
        "resume_rel_diff_per_epoch": [round(r, 9) for r in resume_rel],
        "topology_rel_diff_per_epoch": [round(r, 5) for r in topology_rel],
        "resume_exact": bool(resume_exact),
        "topology_first_epoch_ok": bool(topology_ok),
        "tolerance": args.tolerance,
        "parity_ok": bool(parity_ok),
        # explicit ran/skipped from the have_epochs check — inferring
        # skip from a >1s wall-clock threshold would misreport a
        # genuinely-run sub-second smoke arm as skipped (ADVICE.md)
        "ran": {"single": ran_single,
                "two_process_straight": ran_straight,
                "two_process_resumed": ran_dist},
        # an arm skipped as already-complete reports null seconds, not a
        # meaningless near-zero reparse time
        "seconds": {"single": round(t_single, 1) if ran_single else None,
                    "two_process_straight": (round(t_straight, 1)
                                             if ran_straight else None),
                    "two_process_resumed": (round(t_dist, 1)
                                            if ran_dist else None)},
        "protocol": "arm A: 1 process x 2 virtual CPU devices; arms B/C: "
                    "2 processes x 1 device over jax.distributed (Gloo); "
                    "C restarts both ranks from the shared checkpoint "
                    f"after epoch {args.resume_after}. Resume parity "
                    "(C vs B) asserted bit-exact; topology parity (B vs "
                    "A) asserted on the first epoch only — same per-step "
                    "sample set, different float-reduction order, so "
                    "later epochs drift chaotically (module docstring).",
        "partitioned": partitioned,
        "per_process_logs": sorted(
            os.path.basename(p) for p in os.listdir(work)
            if p.endswith(".log")),
        "workdir": work,
    }
    with open(args.out, "w") as f:
        strict_dump(result, f, indent=2)
    print(strict_dumps(result))

    if args.refresh_multichip is not None:
        refresh_multichip(args.refresh_multichip)
    if not parity_ok:
        raise SystemExit(
            f"parity failed: resume_rel={resume_rel} "
            f"topology_rel={topology_rel}")


if __name__ == "__main__":
    main()
