"""Benchmark: 512x512 network inference throughput on one chip.

Mirrors the reference's pure-network FPS benchmark, INCLUDING its batching:
the reference iterates its train loader and reports
``opt.batch_size / batch_time`` per step — its own inline shape comment
shows ``[8, 512, 512, 3]`` input tensors — so the 38.5 FPS headline
(reference: test_inference_speed.py:90-120, README.md:67) is batched
throughput on a 2080 Ti, not single-image latency.  This benchmark runs
the flagship 4-stack IMHN (bf16 compute) on a batch of 8 synthetic
512x512 images with CHAINED iterations (each step's input depends on the
previous step's output through a scalar), which defeats async dispatch
pipelining — the conservative protocol from tools/perf_audit.py, whose
audited sweep this number reproduces (PERF_AUDIT_B.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Self-protecting: backend bring-up runs under a watchdog (a wedged TPU claim
hangs ``jax.devices()`` forever); on timeout the benchmark re-executes itself
on the CPU backend so the driver still gets a result line (flagged in the
unit string).
"""
import json
import os
import sys
import threading

BASELINE_FPS = 38.5
BATCH = 8
# The axon claim can sit in its bind loop several minutes before either
# granting or raising UNAVAILABLE; give it a generous window before giving
# up on the chip (still leaves >= 20 min for the CPU fallback run).
BACKEND_TIMEOUT_S = 480
TOTAL_TIMEOUT_S = 1800


def _watchdog(seconds, message):
    def fire():
        # allow_nan=False: every field is a finite literal, and the
        # watchdog cannot rely on package imports mid-teardown (JGL004)
        print(json.dumps({
            "metric": "network_inference_fps_512x512_batch8",
            "value": 0.0,
            "unit": f"imgs/sec ({message})",
            "vs_baseline": 0.0,
        }, allow_nan=False), flush=True)
        os._exit(2)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def _backend_ready(timeout_s):
    """True if jax.devices() returns within timeout_s (it can hang or error
    for many minutes when the TPU claim is held by a dead client)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from improved_body_parts_tpu.utils import devices_with_timeout

    try:
        devices_with_timeout(timeout_s)
        return True
    except (RuntimeError, TimeoutError):
        return False


def _provenance():
    """Host/build identity stamped into the bench line so BENCH_*.json
    artifacts are comparable across hosts and commits: a 53 imgs/s line
    from a 2-core cpu-shares container and one from a full host look
    identical without it."""
    import platform as _platform
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=here, capture_output=True,
            text=True, timeout=10).stdout.strip() or None
    except Exception:  # noqa: BLE001 — not a checkout / no git
        sha = None
    try:
        import jax

        jax_version = jax.__version__
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — provenance must never kill the line
        jax_version = backend = None
    try:
        from improved_body_parts_tpu.analysis import (
            GRAFTLINT_VERSION,
            ruleset_hash,
        )

        # version + rule-set hash make lint counts comparable across
        # PRs: a count change means the TREE changed only when the
        # ruleset stamp is identical
        graftlint = {"version": GRAFTLINT_VERSION,
                     "ruleset": ruleset_hash()}
    except Exception:  # noqa: BLE001 — provenance must never kill the line
        graftlint = None
    try:
        from improved_body_parts_tpu.analysis.program import (
            GRAFTAUDIT_VERSION,
            audit_ruleset_hash,
        )

        # same contract as lint: audit verdicts/fingerprints are only
        # compared between identical check sets
        graftaudit = {"version": GRAFTAUDIT_VERSION,
                      "ruleset": audit_ruleset_hash()}
    except Exception:  # noqa: BLE001 — provenance must never kill the line
        graftaudit = None
    return {
        "git_sha": sha,
        "jax_version": jax_version,
        "backend": backend,
        "platform": _platform.platform(),
        "python": _platform.python_version(),
        "cpu_count": os.cpu_count(),
        "graftlint": graftlint,
        "graftaudit": graftaudit,
    }


def _audited_onchip_note():
    """The last audited on-chip figure, read from the audit artifact at
    runtime so the fallback line can never go stale when the audit is
    regenerated (round-3 advisor finding)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "PERF_AUDIT_B.json")
    try:
        with open(path) as f:
            audit = json.load(f)
        batch, stats = max(audit["batches"].items(), key=lambda kv: int(kv[0]))
        return (f"{stats['chained_fps']:.0f} imgs/s b{batch}, "
                "PERF_AUDIT_B.json")
    except Exception:  # noqa: BLE001 — artifact absent/reshaped
        return "see PERF_AUDIT_B.json"


def _serve_bench_summary(fallback, budget_s):
    """Run tools/serve_bench.py (the throughput-under-load benchmark) and
    return a compact summary for the bench line, or an {"error"/"skipped"}
    marker.  Subprocess so its failure or timeout can never take down the
    primary metric; stdout is captured to keep this process's single-
    JSON-line contract.  ``budget_s`` is the wall-clock remaining under
    the driver's total budget — when the chained benchmark already spent
    it, the serve summary is skipped, never the primary line.
    ``IBP_BENCH_SERVE=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_SERVE") == "0":
        return {"skipped": "IBP_BENCH_SERVE=0"}
    if budget_s < 180:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (SERVE_BENCH.json has the full run)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="serve_bench_"),
                       "SERVE_BENCH.json")
    # --no-decode-ab: the decode-lane A/B is the separate budget-gated
    # "decode" key (--decode-only), never paid twice per bench run
    if fallback:
        # CPU: small model at the 512 protocol size (where batch lanes
        # measurably pay even on the host backend), one verdict round —
        # the committed SERVE_BENCH.json carries the full-protocol run
        argv = ["--config", "tiny", "--sizes", "512", "--boxsize", "512",
                "--requests", "3", "--clients", "8", "--max-batch", "4",
                "--max-wait-ms", "400", "--occupancy-first",
                "--rounds", "1", "--planted", "2", "--no-decode-ab"]
        timeout = min(600, budget_s)
    else:
        argv = ["--config", "canonical", "--sizes", "512",
                "--requests", "6", "--clients", "8", "--max-batch", "8",
                "--rounds", "2", "--planted", "2", "--no-decode-ab"]
        timeout = min(900, budget_s)
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools", "serve_bench.py"),
             "--out", out] + argv,
            capture_output=True, timeout=timeout, check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "imgs_per_sec": r["serve_at_peak_load"]["imgs_per_sec"],
            "sequential_imgs_per_sec": r["sequential"]["imgs_per_sec"],
            "p95_ms": r["serve_at_peak_load"]["latency_ms"]["p95"],
            "mean_batch_occupancy":
                r["serve_at_peak_load"]["mean_batch_occupancy"],
            "batched_beats_sequential": r["batched_beats_sequential"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _decode_summary(fallback, budget_s):
    """Run tools/serve_bench.py --decode-only (the fused device-decode
    lane vs the host decode-pool lane, interleaved A/B rounds) and
    return a compact summary, or an {"error"/"skipped"} marker — the
    "serve" key contract.  Subprocess so a decode-bench failure can
    never take down the primary metric; bounded by the REMAINING driver
    budget.  ``IBP_BENCH_DECODE=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_DECODE") == "0":
        return {"skipped": "IBP_BENCH_DECODE=0"}
    if budget_s < 180:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (SERVE_BENCH.json has the full A/B)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="decode_ab_"),
                       "SERVE_BENCH.json")
    if fallback:
        # CPU: small model at the 512 protocol size, fewer rounds —
        # the committed SERVE_BENCH.json carries the full-protocol A/B
        argv = ["--config", "tiny", "--sizes", "512", "--boxsize", "512",
                "--requests", "3", "--clients", "8", "--max-batch", "4",
                "--max-wait-ms", "400", "--occupancy-first",
                "--decode-rounds", "3", "--planted", "2"]
        timeout = min(600, budget_s)
    else:
        argv = ["--config", "canonical", "--sizes", "512",
                "--requests", "6", "--clients", "8", "--max-batch", "8",
                "--decode-rounds", "3", "--planted", "2"]
        timeout = min(900, budget_s)
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools", "serve_bench.py"),
             "--decode-only", "--out", out] + argv,
            capture_output=True, timeout=timeout, check=True,
            env=dict(os.environ))
        with open(out) as f:
            ab = json.load(f)["decode_ab"]
        return {
            "median_round_ratio": ab["median_round_ratio"],
            "device_decode_beats_host_pool":
                ab["device_decode_beats_host_pool"],
            "device_imgs_per_sec": ab["device_imgs_per_sec"],
            "host_pool_imgs_per_sec": ab["host_pool_imgs_per_sec"],
            "decode_fused": ab["decode_fused"],
            "decode_host_fallback": ab["decode_host_fallback"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _stream_summary(fallback, budget_s):
    """Run tools/stream_bench.py (the multi-stream streaming workload:
    N simulated webcams, each an ordered StreamSession pipeline over one
    engine, interleaved multi/single verdict rounds) and return a
    compact summary, or an {"error"/"skipped"} marker — the
    "serve"/"decode" key contract.  Subprocess so a streaming failure
    can never take down the primary metric; bounded by the REMAINING
    driver budget.  ``IBP_BENCH_STREAM=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_STREAM") == "0":
        return {"skipped": "IBP_BENCH_STREAM=0"}
    if budget_s < 180:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (STREAM_BENCH.json has the full run)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="stream_bench_"),
                       "STREAM_BENCH.json")
    if fallback:
        # CPU smoke: tiny model at a small frame size, one round — the
        # committed STREAM_BENCH.json carries the 512-class protocol run
        argv = ["--config", "tiny", "--size", "128", "--boxsize", "128",
                "--streams", "4", "--frames", "6", "--video-frames", "6",
                "--rounds", "1", "--planted", "1", "--max-batch", "4"]
        timeout = min(600, budget_s)
    else:
        argv = ["--config", "canonical", "--size", "512",
                "--streams", "4", "--frames", "8", "--video-frames", "8",
                "--rounds", "2", "--planted", "2", "--max-batch", "8"]
        timeout = min(900, budget_s)
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "stream_bench.py"),
             "--out", out] + argv,
            capture_output=True, timeout=timeout, check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "streams": r["streams"],
            "all_streams_sustained": r["all_streams_sustained"],
            "min_stream_fps": r["min_stream_fps"],
            "per_stream_fps": r["per_stream_fps"],
            "per_stream_p95_ms": r["per_stream_p95_ms"],
            "frames_dropped_total": r["frames_dropped_total"],
            "median_scaling_ratio": r["median_scaling_ratio"],
            "track_ids_stable": r["track_ids_stable_all_rounds"],
            "recompiles_post_warmup": r["recompiles_post_warmup"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _fastpath_summary(fallback, budget_s):
    """Run tools/stream_bench.py --fastpath (temporal-coherence fast
    path: tracker tier + width-only ROI re-inference vs full-frame
    every frame, interleaved A/B rounds + equal-quality protocol) and
    return a compact summary, or an {"error"/"skipped"} marker — the
    "serve"/"decode" key contract.  Subprocess so a fast-path failure
    can never take down the primary metric; bounded by the REMAINING
    driver budget.  ``IBP_BENCH_FASTPATH=0`` skips it
    unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_FASTPATH") == "0":
        return {"skipped": "IBP_BENCH_FASTPATH=0"}
    if budget_s < 300:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (STREAM_FASTPATH.json has the full "
                           "run)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="stream_fastpath_"),
                       "STREAM_FASTPATH.json")
    # planted-canvas == size hugs the planted crowd into the frame's
    # top-left so the width-only ROI window anchors at x0=0 and the
    # crop decode EXACTLY equals the full-frame decode (people_delta=0
    # A/B with no content artifacts); the committed STREAM_FASTPATH.json
    # carries the full protocol run
    if fallback:
        argv = ["--config", "tiny", "--size", "256", "--boxsize", "256",
                "--streams", "2", "--frames", "12",
                "--video-frames", "8", "--rounds", "1",
                "--planted", "2", "--planted-canvas", "256",
                "--max-batch", "2", "--fastpath",
                "--fp-roi-width", "128", "--fp-roi-margin", "16",
                "--fp-quality-frames", "12"]
        timeout = min(720, budget_s)
    else:
        argv = ["--config", "canonical", "--size", "512",
                "--streams", "4", "--frames", "16",
                "--video-frames", "8", "--rounds", "2",
                "--planted", "2", "--planted-canvas", "512",
                "--max-batch", "4", "--fastpath",
                "--fp-roi-width", "256", "--fp-roi-margin", "32",
                "--fp-quality-frames", "16"]
        timeout = min(900, budget_s)
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "stream_bench.py"),
             "--out", out] + argv,
            capture_output=True, timeout=timeout, check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "median_fastpath_speedup": r["median_fastpath_speedup"],
            "fastpath_speedup_sustained":
                r["fastpath_speedup_sustained"],
            "skip_rate": r["fastpath_skip_rate"],
            "roi_rate": r["fastpath_roi_rate"],
            "conservation_exact":
                r["fastpath_conservation"]["exact"],
            "quality_equal_all_scenes": r["quality_equal_all_scenes"],
            "recompiles_post_warmup": r["recompiles_post_warmup"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _onchip_summary(fallback, budget_s):
    """Run the ISSUE 20 on-chip campaign smoke — tools/tta_bench.py
    --ab (fused multi-scale TTA vs the per-entry dispatch loop,
    payload-equality + AP-parity gated) plus tools/pallas_check.py
    --peaks --limbs (interpreter-parity rows for the Pallas decode
    kernels) — and return a compact summary, or an {"error"/"skipped"}
    marker under the "serve"/"decode" key contract.  Subprocess so an
    on-chip-campaign failure can never take down the primary metric;
    bounded by the REMAINING driver budget.  ``IBP_BENCH_ONCHIP=0``
    skips it unconditionally.  The speedup gate only binds off-CPU
    (TTA_AB.json carries the full protocol + the CPU
    inter-program-parallelism caveat)."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_ONCHIP") == "0":
        return {"skipped": "IBP_BENCH_ONCHIP=0"}
    if budget_s < 240:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (TTA_AB.json / PALLAS_CHECK.json "
                           "have the full runs)"}
    here = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="onchip_")
    ab_out = os.path.join(tmp, "TTA_AB.json")
    pk_out = os.path.join(tmp, "PALLAS_CHECK.json")
    if fallback:
        ab_argv = ["--config", "tiny", "--num-images", "2",
                   "--rounds", "1", "--size", "128",
                   "--scales", "0.5,1.0", "--rotations", "0,30",
                   "--telemetry-sink", "none"]
        pk_iters = "3"
        timeout = min(420, budget_s)
    else:
        ab_argv = ["--config", "tiny", "--num-images", "4",
                   "--rounds", "3", "--size", "128",
                   "--telemetry-sink", "none"]
        pk_iters = "10"
        timeout = min(600, budget_s)
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools", "tta_bench.py"),
             "--ab", "--out", ab_out] + ab_argv,
            capture_output=True, timeout=timeout, check=True,
            env=dict(os.environ))
        with open(ab_out) as f:
            ab = json.load(f)
        # the kernels are interpreter-mode on every platform here; a
        # real chip re-blesses via pallas_check --json (the committed
        # PALLAS_CHECK.json workflow)
        subprocess.run(
            [sys.executable,
             os.path.join(here, "tools", "pallas_check.py"),
             "--peaks", "--limbs", "--interpret", "--iters", pk_iters,
             "--hw", "64", "--json", pk_out],
            capture_output=True,
            timeout=max(60, min(300, budget_s - timeout)), check=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        with open(pk_out) as f:
            pk = json.load(f)
        return {
            "payload_equal_all_images": ab["payload_equal_all_images"],
            "ap_parity_equal": ab["ap_parity"]["equal"],
            "median_fused_speedup": ab["median_fused_speedup"],
            "fused_speedup_gate_binding":
                ab["fused_speedup_gate_binding"],
            "median_fused_dispatches_per_image":
                ab["median_fused_dispatches_per_image"],
            "median_looped_dispatches_per_image":
                ab["median_looped_dispatches_per_image"],
            "recompiles_post_warmup": ab["recompiles_post_warmup"],
            "pallas_decode_parity_ok": pk["parity_ok"],
            "pallas_kernels": [r["kernel"] for r in pk["kernels"]],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _feed_rate_summary(fallback, budget_s):
    """Run tools/feed_rate.py (sync vs shm-worker input feed rate) and
    return a compact summary for the bench line, or an {"error"/"skipped"}
    marker — mirroring the "serve" key's contract.  Subprocess so a feed
    failure or timeout can never take down the primary metric; bounded by
    the REMAINING driver budget.  ``IBP_BENCH_FEED=0`` skips it
    unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_FEED") == "0":
        return {"skipped": "IBP_BENCH_FEED=0"}
    if budget_s < 120:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (INPUT_PIPELINE.json has the full run)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="feed_rate_"),
                       "INPUT_PIPELINE.json")
    # small corpus, short windows, host-GT only via --max-people default
    # rows; the committed INPUT_PIPELINE.json carries the full protocol
    argv = ["--records", "12", "--batch", "4", "--min-seconds", "6",
            "--workers", "0,2", "--config",
            "tiny" if fallback else "canonical"]
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools", "feed_rate.py"),
             "--out", out] + argv,
            capture_output=True, timeout=min(420, budget_s), check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        rows = {(row["mode"], row["pipeline"], row["workers"]):
                row["samples_per_sec"] for row in r["rows"]}
        sync = rows.get(("host_gt", "sync", 0))
        shm2 = rows.get(("host_gt", "shm", 2))
        return {
            "wire": r.get("wire"),
            "sync_samples_per_sec": sync,
            "shm_w2_samples_per_sec": shm2,
            "shm_vs_sync": (round(shm2 / sync, 2)
                            if sync and shm2 else None),
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _telemetry_summary(fallback, budget_s):
    """Run tools/telemetry_overhead.py (the obs-subsystem overhead check:
    30 synthetic train steps with the event sink + attribution ON vs OFF,
    interleaved rounds) and return a compact summary, or an
    {"error"/"skipped"} marker — the "serve"/"feed" key contract.
    Subprocess so a telemetry failure can never take down the primary
    metric; bounded by the REMAINING driver budget.
    ``IBP_BENCH_TELEMETRY=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_TELEMETRY") == "0":
        return {"skipped": "IBP_BENCH_TELEMETRY=0"}
    if budget_s < 90:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (run tools/telemetry_overhead.py "
                           "directly for the full check)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="telemetry_oh_"),
                       "TELEMETRY_OVERHEAD.json")
    # the tiny config keeps the A/B inside the budget on both backends;
    # overhead is per-window bookkeeping, so it only SHRINKS relative to
    # the canonical config's much longer steps
    argv = ["--config", "tiny", "--steps", "10", "--print-freq", "5",
            "--rounds", "15"]
    try:
        subprocess.run(
            [sys.executable,
             os.path.join(here, "tools", "telemetry_overhead.py"),
             "--out", out] + argv,
            capture_output=True, timeout=min(600, budget_s), check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "step_ms_off": r["step_ms_off"],
            "step_ms_on": r["step_ms_on"],
            "overhead_pct": r["overhead_pct"],
            "within_budget": r["within_budget"],
            "off_round_spread_pct": r["off_round_spread_pct"],
            "split_covers_wall_frac": r["split_covers_wall_frac"],
            "recompiles_post_warmup": r["recompiles_post_warmup"],
            "events": r["telemetry_events"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _ckpt_summary(fallback, budget_s):
    """Run tools/ckpt_bench.py (sync vs async epoch-boundary checkpoint
    stall on a real multi-epoch fit, + bit-identity + write/eval overlap
    from the span trace) and return a compact summary, or an
    {"error"/"skipped"} marker — the "serve"/"feed"/"telemetry" key
    contract.  Subprocess so a checkpoint failure can never take down
    the primary metric; bounded by the REMAINING driver budget.
    ``IBP_BENCH_CKPT=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_CKPT") == "0":
        return {"skipped": "IBP_BENCH_CKPT=0"}
    if budget_s < 120:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (CKPT_BENCH.json has the full run)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="ckpt_bench_"),
                       "CKPT_BENCH.json")
    # tiny config either way: the stall is host-side (snapshot vs full
    # Orbax write), so the verdict transfers; the committed
    # CKPT_BENCH.json carries the full-protocol run
    argv = ["--config", "tiny", "--rounds", "2", "--epochs", "2"]
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools", "ckpt_bench.py"),
             "--out", out] + argv,
            capture_output=True, timeout=min(600, budget_s), check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "sync_stall_ms": r["sync_stall_ms_mean"],
            "async_stall_ms": r["async_stall_ms_mean"],
            "stall_reduction": r["stall_reduction"],
            "meets_target": r["meets_target"],
            "bit_identical_restore": r["bit_identical_restore"],
            "write_overlaps_step_or_eval": r["write_overlaps_step_or_eval"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _chaos_summary(fallback, budget_s):
    """Run tools/chaos_train.py (the elastic-training fault-injection
    harness: randomized kills of a real supervised fit, relaunch until
    the epoch target lands) and return a compact summary, or an
    {"error"/"skipped"} marker — the "serve"/"feed"/"telemetry"/"ckpt"
    key contract.  Subprocess so a chaos failure can never take down
    the primary metric; bounded by the REMAINING driver budget.
    ``IBP_BENCH_CHAOS=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_CHAOS") == "0":
        return {"skipped": "IBP_BENCH_CHAOS=0"}
    if budget_s < 240:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (CHAOS.json has the full sweep)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="chaos_train_"),
                       "CHAOS.json")
    # short sweep, no control arm: the bench key checks the recovery
    # machinery end to end (kill -> classify -> resume-on-last-committed
    # -> no leaks); the committed CHAOS.json carries the full 8-kill
    # randomized sweep WITH the bit-match against an uninterrupted
    # control run.  Tiny config either way — chaos exercises the
    # supervisor, not the model.
    argv = ["--config", "tiny", "--kills", "3", "--epochs", "2",
            "--no-control"]
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools", "chaos_train.py"),
             "--out", out] + argv,
            capture_output=True, timeout=min(900, budget_s), check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "ok": r["ok"],
            "completed": r["completed"],
            "injections_done": r["injections_done"],
            "segments_total": r["segments_total"],
            "all_resumes_on_last_committed":
                r["all_resumes_on_last_committed"],
            "leaked_pids_total": r["leaked_pids_total"],
            "writer_thread_leaked": r["writer_thread_leaked"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _servechaos_summary(fallback, budget_s):
    """Run tools/chaos_serve.py (the serve-side fault-injection harness:
    an EnginePool over shared-nothing batcher replicas under wedge /
    poison / decode-pool-kill / hard-stop / latency-spike injections)
    and return a compact summary, or an {"error"/"skipped"} marker —
    the "chaos" key contract.  Subprocess so a chaos failure can never
    take down the primary metric; bounded by the REMAINING driver
    budget.  ``IBP_BENCH_SERVECHAOS=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_SERVECHAOS") == "0":
        return {"skipped": "IBP_BENCH_SERVECHAOS=0"}
    if budget_s < 240:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (SERVE_CHAOS.json has the full "
                           "sweep)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="chaos_serve_"),
                       "SERVE_CHAOS.json")
    # smoke sweep: fewer requests/frames than the committed artifact,
    # tiny config either way — serve chaos exercises the pool/breaker/
    # failover machinery, not the model
    argv = ["--config", "tiny", "--size", "128", "--boxsize", "128",
            "--replicas", "2", "--requests", "4", "--streams", "2",
            "--frames", "6", "--planted", "1"]
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "chaos_serve.py"),
             "--out", out] + argv,
            capture_output=True, timeout=min(900, budget_s), check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "ok": r["ok"],
            "injections": [i["kind"] for i in r["injections"]],
            "futures_tracked": r["futures"]["tracked"],
            "futures_lost": r["futures"]["lost"],
            "recompiles_post_warmup": r["recompiles_post_warmup"],
            "leaked_threads": len(r["leaked_threads"]),
            "checks_failed": r["checks_failed"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _procpool_summary(fallback, budget_s):
    """Run tools/serve_bench.py --proc-only (the thread-pool vs
    process-pool A/B over the shared-memory wire plus the SIGKILL
    chaos arm) and return a compact summary, or an {"error"/"skipped"}
    marker — the "chaos" key contract.  Subprocess so a worker-process
    failure can never take down the primary metric; bounded by the
    REMAINING driver budget.  ``IBP_BENCH_PROCPOOL=0`` skips it
    unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_PROCPOOL") == "0":
        return {"skipped": "IBP_BENCH_PROCPOOL=0"}
    if budget_s < 240:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (PROC_BENCH.json has the full "
                           "A/B)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="proc_bench_"),
                       "PROC_BENCH.json")
    # smoke A/B: fewer rounds/requests than the committed artifact —
    # the verdict machinery, wire and chaos arm are what's exercised
    argv = ["--proc-only", "--proc-rounds", "3", "--requests", "8",
            "--telemetry-sink", "none"]
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "serve_bench.py"),
             "--out", out] + argv,
            capture_output=True, timeout=min(900, budget_s), check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        ab, chaos = r["proc_ab"], r["proc_chaos"]
        return {
            "verdict_ok": ab["verdict_ok"],
            "multi_core_host": ab["multi_core_host"],
            "median_round_ratio": ab["median_round_ratio"],
            "workers": ab["workers"],
            "recompiles_post_warmup": r["recompiles_post_warmup"],
            "chaos_all_futures_resolved": chaos["all_futures_resolved"],
            "chaos_respawned": chaos["respawned"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _fleetobs_summary(fallback, budget_s):
    """Run tools/fleet_audit.py --quick (the fleet observability plane:
    obs-on/off A/B over a 2-worker ProcessRouter, cross-boundary
    conservation, merged-scrape check, trace stitching, SIGKILL
    postmortem) and return a compact summary, or an {"error"/"skipped"}
    marker — the "chaos" key contract.  Subprocess so a worker-process
    failure can never take down the primary metric; bounded by the
    REMAINING driver budget.  ``IBP_BENCH_FLEETOBS=0`` skips it
    unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_FLEETOBS") == "0":
        return {"skipped": "IBP_BENCH_FLEETOBS=0"}
    if budget_s < 240:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (FLEET_OBS.json has the full "
                           "audit)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="fleet_obs_"),
                       "FLEET_OBS.json")
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "fleet_audit.py"),
             "--quick", "--out", out],
            capture_output=True, timeout=min(900, budget_s), check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "ok": r["ok"],
            "overhead_median_pct":
                r["overhead"]["paired_median_overhead_pct"],
            "conservation_frac": r["conservation"]["frac"],
            "compiles_ok": r["compiles"]["ok"],
            "scrape_ok": r["scrape"]["ok"],
            "stitch_ok": r["trace_stitch"]["ok"],
            "postmortem_ok": r["chaos"]["postmortem_ok"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _history_summary(fallback, budget_s):
    """Run tools/history_audit.py --quick (the telemetry-history layer:
    sampler-on/off A/B over a 2-worker ProcessRouter, exact counter
    conservation across registry/history/router, gap accounting,
    /history + /query routes, capacity fit, replay bit-identity) and
    return a compact summary, or an {"error"/"skipped"} marker — the
    "chaos" key contract.  Subprocess so a worker-process failure can
    never take down the primary metric; bounded by the REMAINING driver
    budget.  ``IBP_BENCH_HISTORY=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_HISTORY") == "0":
        return {"skipped": "IBP_BENCH_HISTORY=0"}
    if budget_s < 240:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (HISTORY_AUDIT.json has the full "
                           "audit)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="history_audit_"),
                       "HISTORY_AUDIT.json")
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "history_audit.py"),
             "--quick", "--out", out],
            capture_output=True, timeout=min(900, budget_s), check=True,
            env=dict(os.environ))
        with open(out) as f:
            r = json.load(f)
        return {
            "ok": r["ok"],
            "overhead_median_pct":
                r["overhead"]["paired_median_overhead_pct"],
            "conservation_ok": r["conservation"]["ok"],
            "gaps_ok": r["gaps"]["ok"],
            "routes_ok": r["routes"]["ok"],
            "capacity_knee_qps": r["capacity"]["fit"]["knee_qps"],
            "replay_bit_identical": r["replay"]["replay_bit_identical"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _audit_summary(budget_s):
    """Run tools/program_audit.py (the graftaudit compiled-program tier:
    jaxpr checks + fingerprint gating over the program registry, at
    trace level for speed — the committed PROGRAM_AUDIT.json carries
    the full AOT sweep) and return verdict counts, or an
    {"error"/"skipped"} marker — the "lint" key contract.  Subprocess
    so an auditor crash can never take down the primary metric.
    ``IBP_BENCH_AUDIT=0`` skips it unconditionally."""
    import subprocess

    if os.environ.get("IBP_BENCH_AUDIT") == "0":
        return {"skipped": "IBP_BENCH_AUDIT=0"}
    if budget_s < 180:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (run tools/program_audit.py directly)"}
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "program_audit.py"),
             "--level", "trace", "--format", "json"],
            capture_output=True, text=True, timeout=min(600, budget_s),
            env=dict(os.environ))
        if proc.returncode not in (0, 1):
            return {"error": f"exit {proc.returncode}"}
        r = json.loads(proc.stdout)
        drifted = sum(1 for p in r["programs"].values() if p["drift"])
        return {
            "ok": r["ok"],
            "programs": len(r["programs"]),
            "errors": r["counts"]["error"],
            "warnings": r["counts"]["warning"],
            "drifted": drifted,
            "level": r["level"],
            "version": r["graftaudit"]["version"],
            "ruleset": r["graftaudit"]["ruleset"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _scaling_summary(fallback, budget_s):
    """Run tools/scaling_test.py (the GSPMD weak-scaling curve: the
    partitioned donated train step over virtual CPU meshes, interleaved
    rounds, monotone-throughput verdict) as a budget-bounded smoke and
    return a compact summary, or an {"error"/"skipped"} marker — the
    "serve"/"feed" key contract.  Subprocess so a partitioning failure
    can never take down the primary metric; the committed SCALING.json
    carries the full protocol run.  ``IBP_BENCH_SCALING=0`` skips it
    unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_SCALING") == "0":
        return {"skipped": "IBP_BENCH_SCALING=0"}
    if budget_s < 240:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (SCALING.json has the full run)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="scaling_"), "SCALING.json")
    # smoke shape: two mesh sizes, short segments, small canvas — the
    # committed SCALING.json carries the full n=1/2/4/8 curve.
    # imhn_fsdp shards over the composite ('data','model') axis, so
    # even the 2-device smoke carries sharded state; the CPU-fallback
    # host gets the shortest segments (same discipline as the other
    # fallback-aware keys)
    segs = (["--steps", "4", "--rounds", "2"] if fallback
            else ["--steps", "6", "--rounds", "3"])
    argv = ["--devices", "1", "2", "--image-size", "64",
            "--config", "tiny", "--rules", "imhn_fsdp",
            "--tolerance", "0.5"] + segs
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # virtual mesh — never claims the chip
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "scaling_test.py"),
             "--out", out] + argv,
            capture_output=True, timeout=min(600, budget_s), check=True,
            env=env)
        with open(out) as f:
            r = json.load(f)
        largest = str(max(int(n) for n in r["results"]))
        return {
            "devices": r["devices"],
            "imgs_per_sec_medians": r["imgs_per_sec_medians"],
            "monotone_ok": r["monotone_ok"],
            "partition_rules": r["partition_rules"]["name"],
            "sharded_state_leaves":
                r["results"][largest]["state_leaves"]["sharded"],
            "loss_parity_rel": r["loss_parity"]["rel_diff"],
            "loss_parity_ok": r["loss_parity"]["ok"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _cascade_summary(fallback, budget_s):
    """Run tools/cascade_bench.py (two-tier student/teacher cascade vs
    teacher-only, interleaved rounds) and return a compact summary, or
    an {"error"/"skipped"} marker — the "serve"/"decode" key contract.
    Subprocess so a cascade failure can never take down the primary
    metric; the committed CASCADE_BENCH.json carries the full protocol
    run.  ``IBP_BENCH_CASCADE=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_CASCADE") == "0":
        return {"skipped": "IBP_BENCH_CASCADE=0"}
    if budget_s < 420:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (CASCADE_BENCH.json has the full "
                           "run)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="cascade_"),
                       "CASCADE_BENCH.json")
    # smoke shape: fewer/shorter rounds than the committed artifact;
    # the production-shape synth_deep pair keeps the ratio meaningful
    # (the tiny pair's shared extraction cost drowns the forward delta)
    argv = ["--rounds", "2", "--clients", "2", "--requests", "4"]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # CPU protocol — never claims the chip
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "cascade_bench.py"),
             "--out", out] + argv,
            capture_output=True, timeout=min(900, budget_s), check=True,
            env=env)
        with open(out) as f:
            r = json.load(f)
        return {
            "median_round_ratio": r["median_round_ratio"],
            "cascade_beats_target": r["cascade_beats_target"],
            "escalation_rate": r["escalation_rate"],
            "answered_student": r["cascade_routing"]["answered_student"],
            "escalated_teacher":
                r["cascade_routing"]["escalated_teacher"],
            "ap_rel_diff": r["quality"]["rel_diff"],
            "ap_within_tolerance": r["quality"]["within_tolerance"],
            "recompiles_post_warmup": r["recompiles_post_warmup"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _slo_summary(fallback, budget_s):
    """Run tools/latency_audit.py --quick (the request-tracing + SLO
    layer's proof sweep: per-hop conservation, causal completeness
    under failover/hedge churn, reqtrace overhead, recompile check) and
    return the gates, or an {"error"/"skipped"} marker — the
    "serve"/"decode" key contract.  Subprocess so an audit failure can
    never take down the primary metric; the committed
    LATENCY_AUDIT.json carries the full protocol run.
    ``IBP_BENCH_SLO=0`` skips it unconditionally."""
    import subprocess
    import tempfile

    if os.environ.get("IBP_BENCH_SLO") == "0":
        return {"skipped": "IBP_BENCH_SLO=0"}
    if budget_s < 240:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (LATENCY_AUDIT.json has the full "
                           "run)"}
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.mkdtemp(prefix="slo_"),
                       "LATENCY_AUDIT.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # CPU protocol — never claims the chip
    try:
        subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "latency_audit.py"),
             "--quick", "--out", out],
            capture_output=True, timeout=min(600, budget_s), check=True,
            env=env)
        with open(out) as f:
            r = json.load(f)
        return {
            "gates": r["gates"],
            "plain_conservation":
                r["plain_serve"]["registry_conservation_frac"],
            "chain_coverage_p50":
                r["plain_serve"]["chain_coverage_p50"],
            "failover_edges": r["chaos"]["failover_edges"],
            "hedge_edges": r["chaos"]["hedge_edges"],
            "reqtrace_overhead_pct":
                r["reqtrace_overhead"]["overhead_pct"],
            "recompiles_post_warmup": r["recompiles_post_warmup"],
            "slo_status": r["slo"]["status"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def _lint_summary(budget_s):
    """Run tools/lint.py (the graftlint static-analysis gate) and return
    finding counts by severity, or an {"error"/"skipped"} marker — the
    "serve"/"feed"/... key contract.  Subprocess so a linter crash can
    never take down the primary metric; the scan is pure-host AST work
    (seconds), so the budget floor is small.  ``IBP_BENCH_LINT=0`` skips
    it unconditionally."""
    import subprocess

    if os.environ.get("IBP_BENCH_LINT") == "0":
        return {"skipped": "IBP_BENCH_LINT=0"}
    if budget_s < 60:
        return {"skipped": f"only {budget_s:.0f}s left in the bench "
                           "budget (run tools/lint.py directly)"}
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "tools", "lint.py"),
             "--format", "json", "--fail-on", "never"],
            capture_output=True, text=True, timeout=min(300, budget_s),
            check=True, env=dict(os.environ))
        r = json.loads(proc.stdout)
        return {
            "files": r["files"],
            "errors": r["counts"]["error"],
            "warnings": r["counts"]["warning"],
            "info": r["counts"]["info"],
            "suppressed": r["suppressed"],
            "version": r["version"],
            "ruleset": r["ruleset"],
        }
    except Exception as e:  # noqa: BLE001 — the primary metric must land
        return {"error": f"{type(e).__name__}"}


def main():
    import time

    t_start = time.monotonic()
    total = _watchdog(TOTAL_TIMEOUT_S, "timeout")

    fallback = os.environ.get("IBP_BENCH_CPU_FALLBACK") == "1"
    if not fallback and not _backend_ready(BACKEND_TIMEOUT_S):
        # re-exec on CPU; the stuck backend thread dies with this process
        env = dict(os.environ)
        env["IBP_BENCH_CPU_FALLBACK"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)],
                  env)

    import jax

    if fallback:
        # belt-and-braces: the env vars set by the re-exec are not always
        # honoured once a sitecustomize has registered an accelerator
        # plugin; the config update is what actually sticks
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — backend already initialized
            pass

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from improved_body_parts_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    import jax.numpy as jnp

    from __graft_entry__ import entry
    from improved_body_parts_tpu.utils import chained_time

    forward, (variables, imgs) = entry()
    batch = 2 if fallback else BATCH
    imgs = jnp.broadcast_to(imgs[0], (batch, *imgs.shape[1:]))

    # chained steps: input i+1 depends on output i — defeats dispatch
    # pipelining, so the measured time is true serialized step latency
    # (the shared utils.profiling.chained_time protocol)
    dt = chained_time(forward, variables, imgs,
                      iters=1 if fallback else 50,
                      warmup=1 if fallback else 5)

    fps = batch / dt
    unit = (f"imgs/sec (cpu-fallback, batch {batch}; TPU claim unavailable "
            f"— last audited on-chip: {_audited_onchip_note()})"
            if fallback
            else f"imgs/sec (batch {batch}, chained steps; the reference's "
                 "38.5 is batched loader throughput)")
    total.cancel()
    # throughput under concurrent load (the serving engine), bounded by
    # the REMAINING driver budget — the primary metric above is already
    # computed, so a serve failure can only cost this one extra field
    serve = _serve_bench_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # fused device decode vs host decode pool, same budget discipline
    decode = _decode_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # multi-stream streaming workload (sessions + tracker), same
    # discipline
    stream = _stream_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # temporal-coherence fast path (tracker tier + ROI re-inference vs
    # full-frame every frame), same discipline
    fastpath = _fastpath_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # on-chip campaign smoke (fused-TTA A/B + Pallas decode kernel
    # parity), same discipline
    onchip = _onchip_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # input feed rate (sync vs shm workers), same budget discipline
    feed = _feed_rate_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # telemetry overhead (obs/ sink on vs off), same budget discipline
    telemetry = _telemetry_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # epoch-boundary checkpoint stall (sync vs async), same discipline
    ckpt = _ckpt_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # elastic-training fault injection (kill/resume/leak sweep), same
    # discipline
    chaos = _chaos_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # serve-side fault injection (pool wedge/poison/hard-stop sweep),
    # same discipline
    servechaos = _servechaos_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # thread-pool vs process-pool A/B + worker-SIGKILL arm, same
    # discipline
    procpool = _procpool_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # fleet observability plane (obs-on/off A/B, conservation, scrape,
    # stitch, postmortem), same discipline
    fleetobs = _fleetobs_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # telemetry-history layer (sampler-on/off A/B, exact conservation,
    # gap accounting, routes, capacity fit, replay bit-identity), same
    # discipline
    history = _history_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # GSPMD weak-scaling smoke (partitioned step, virtual meshes), same
    # discipline
    scaling = _scaling_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # two-tier cascade serving (student lane + teacher escalation),
    # same discipline
    cascade = _cascade_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # request-path tracing + SLO layer (hop conservation, causal
    # completeness, reqtrace overhead), same discipline
    slo = _slo_summary(
        fallback, TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # static-analysis gate (graftlint), same discipline
    lint = _lint_summary(
        TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    # compiled-program audit (graftaudit registry sweep), same
    # discipline
    audit = _audit_summary(
        TOTAL_TIMEOUT_S - 60 - (time.monotonic() - t_start))
    from improved_body_parts_tpu.obs.events import strict_dumps

    print(strict_dumps({
        # metric name carries the ACTUAL batch (the fallback runs batch 2)
        "metric": f"network_inference_fps_512x512_batch{batch}",
        "value": round(fps, 2),
        "unit": unit,
        "vs_baseline": round(fps / BASELINE_FPS, 3),
        "serve": serve,
        "decode": decode,
        "stream": stream,
        "fastpath": fastpath,
        "onchip": onchip,
        "feed": feed,
        "telemetry": telemetry,
        "ckpt": ckpt,
        "chaos": chaos,
        "servechaos": servechaos,
        "procpool": procpool,
        "fleetobs": fleetobs,
        "history": history,
        "scaling": scaling,
        "cascade": cascade,
        "slo": slo,
        "lint": lint,
        "audit": audit,
        "provenance": _provenance(),
    }))


if __name__ == "__main__":
    main()
