"""Benchmark: single-image 512x512 network inference FPS on one chip.

Mirrors the reference's pure-network FPS benchmark
(reference: test_inference_speed.py:90-120; baseline 38.5 FPS on a 2080 Ti,
README.md:67) on the flagship 4-stack IMHN with bf16 compute.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Self-protecting: backend bring-up runs under a watchdog (a wedged TPU claim
hangs ``jax.devices()`` forever); on timeout the benchmark re-executes itself
on the CPU backend so the driver still gets a result line (flagged in the
unit string).
"""
import json
import os
import sys
import threading
import time

BASELINE_FPS = 38.5
# The axon claim can sit in its bind loop several minutes before either
# granting or raising UNAVAILABLE; give it a generous window before giving
# up on the chip (still leaves >= 20 min for the CPU fallback run).
BACKEND_TIMEOUT_S = 480
TOTAL_TIMEOUT_S = 1800


def _watchdog(seconds, message):
    def fire():
        print(json.dumps({
            "metric": "single_image_512x512_inference_fps",
            "value": 0.0,
            "unit": f"imgs/sec ({message})",
            "vs_baseline": 0.0,
        }), flush=True)
        os._exit(2)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def _backend_ready(timeout_s):
    """True if jax.devices() returns within timeout_s (it can hang or error
    for many minutes when the TPU claim is held by a dead client)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from improved_body_parts_tpu.utils import devices_with_timeout

    try:
        devices_with_timeout(timeout_s)
        return True
    except (RuntimeError, TimeoutError):
        return False


def main():
    total = _watchdog(TOTAL_TIMEOUT_S, "timeout")

    fallback = os.environ.get("IBP_BENCH_CPU_FALLBACK") == "1"
    if not fallback and not _backend_ready(BACKEND_TIMEOUT_S):
        # re-exec on CPU; the stuck backend thread dies with this process
        env = dict(os.environ)
        env["IBP_BENCH_CPU_FALLBACK"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)],
                  env)

    import jax

    if fallback:
        # belt-and-braces: the env vars set by the re-exec are not always
        # honoured once a sitecustomize has registered an accelerator
        # plugin; the config update is what actually sticks
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — backend already initialized
            pass

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from __graft_entry__ import entry

    forward, (variables, imgs) = entry()
    fn = jax.jit(forward)

    out = fn(variables, imgs)  # compile (also the warmup on the slow path)
    jax.block_until_ready(out)

    warmup = 1 if fallback else 5
    for _ in range(warmup):
        out = fn(variables, imgs)
    jax.block_until_ready(out)

    iters = 3 if fallback else 50
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(variables, imgs)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    fps = iters / dt
    unit = "imgs/sec (cpu-fallback)" if fallback else "imgs/sec"
    total.cancel()
    print(json.dumps({
        "metric": "single_image_512x512_inference_fps",
        "value": round(fps, 2),
        "unit": unit,
        "vs_baseline": round(fps / BASELINE_FPS, 3),
    }))


if __name__ == "__main__":
    main()
