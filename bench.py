"""Benchmark: single-image 512x512 network inference FPS on one chip.

Mirrors the reference's pure-network FPS benchmark
(reference: test_inference_speed.py:90-120; baseline 38.5 FPS on a 2080 Ti,
README.md:67) on the flagship 4-stack IMHN with bf16 compute.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

BASELINE_FPS = 38.5


def main():
    import jax

    sys.path.insert(0, ".")
    from __graft_entry__ import entry

    forward, (variables, imgs) = entry()
    fn = jax.jit(forward)

    out = fn(variables, imgs)  # compile
    jax.block_until_ready(out)

    # warmup
    for _ in range(5):
        out = fn(variables, imgs)
    jax.block_until_ready(out)

    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(variables, imgs)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    fps = iters / dt
    print(json.dumps({
        "metric": "single_image_512x512_inference_fps",
        "value": round(fps, 2),
        "unit": "imgs/sec",
        "vs_baseline": round(fps / BASELINE_FPS, 3),
    }))


if __name__ == "__main__":
    main()
